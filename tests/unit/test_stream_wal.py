"""Unit tests for the crash-safe stream journal (stream/wal.py):

- append/recover round trip: a journal replayed after a simulated crash
  reconstructs buffer + drift state BITWISE (state_dict equality covers the
  reservoir RNG state, so all future absorbs are identical too),
- snapshot + WAL truncation + snapshot-based recovery,
- digest mismatch / stale WAL -> fresh start (stale state never replayed),
- a torn FINAL line is dropped, a torn middle line raises,
- seq contiguity: a gap in the WAL raises,
- restart() re-keys and wipes after a blue/green swap,
- wal_append / wal_recover trace events satisfy scripts/check_trace.py's
  contiguity contract.

Numpy-only: the buffer/drift state machines don't need jax, and a fit-free
FakeModel (just ``.data``) keys the buffer's training-row hash set.
"""

import json
import os
import types

import numpy as np
import pytest

from hdbscan_tpu.stream.buffer import IngestBuffer
from hdbscan_tpu.stream.drift import DriftDetector
from hdbscan_tpu.stream.wal import StreamJournal


def _model(seed=0, n=64, dims=3):
    rng = np.random.default_rng(seed)
    return types.SimpleNamespace(data=rng.normal(0, 1, (n, dims)))


def _fresh(model, seed=0):
    """A (buffer, drift) pair in their post-construction state."""
    buf = IngestBuffer(model, reservoir_size=32, seed=seed)
    rng = np.random.default_rng(seed + 100)
    drift = DriftDetector(rng.uniform(0, 1, 512), rng.integers(-1, 3, 512))
    return buf, drift


def _chunk(rng, rows=16, dims=3):
    """One synthetic predicted batch: (points, labels, prob, scores)."""
    return (
        rng.normal(0, 2, (rows, dims)),
        rng.integers(-1, 3, rows),
        rng.uniform(0, 1, rows),
        rng.uniform(0, 1, rows),
    )


def _ingest(journal, buf, drift, chunk):
    """The server's ingest ordering: absorb/update, then append+snapshot."""
    pts, labels, prob, scores = chunk
    buf.absorb(pts, labels, prob)
    drift.update(labels, scores)
    if journal is not None:
        journal.append_ingest(pts, labels, prob, scores)
        journal.maybe_snapshot(buf, drift)


def test_recovery_is_bitwise_identical(tmp_path):
    model = _model()
    rng = np.random.default_rng(1)
    chunks = [_chunk(rng) for _ in range(10)]

    # Uninterrupted reference run (no journal needed).
    ref_buf, ref_drift = _fresh(model)
    for c in chunks:
        _ingest(None, ref_buf, ref_drift, c)

    # Journaled run killed after 6 chunks: nothing closed, file handle
    # simply abandoned — exactly what SIGKILL leaves behind (every append
    # was fsync'd, so the WAL is complete).
    buf_a, drift_a = _fresh(model)
    jr_a = StreamJournal(str(tmp_path), snapshot_every=4)
    jr_a.open("digest-1", buf_a, drift_a)
    for c in chunks[:6]:
        _ingest(jr_a, buf_a, drift_a, c)

    # Recovery process: fresh objects, same directory.
    buf_b, drift_b = _fresh(model)
    jr_b = StreamJournal(str(tmp_path), snapshot_every=4)
    info = jr_b.open("digest-1", buf_b, drift_b)
    # snapshot_every=4 counts the begin record: snapshot landed after
    # ingest 3 (watermark 4), leaving ingests 4-6 in the WAL tail.
    assert info["snapshot"] is True
    assert info["records"] == 3
    assert not info["stale_discarded"] and not info["torn_tail_dropped"]

    # Continue the stream where the crash left off.
    for c in chunks[6:]:
        _ingest(jr_b, buf_b, drift_b, c)

    # Bitwise equality of the full state, RNG included...
    assert buf_b.state_dict() == ref_buf.state_dict()
    assert drift_b.state_dict() == ref_drift.state_dict()
    # ...hence the refit pool is bitwise identical.
    np.testing.assert_array_equal(
        buf_b.refit_points(originals=16, seed=7),
        ref_buf.refit_points(originals=16, seed=7),
    )
    jr_a.close()
    jr_b.close()


def test_snapshot_truncates_wal(tmp_path):
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path), snapshot_every=3)
    jr.open("d", buf, drift)
    rng = np.random.default_rng(2)
    for _ in range(3):
        _ingest(jr, buf, drift, _chunk(rng))
    # begin + ingest1 + ingest2 hit snapshot_every=3: snapshot at
    # watermark 3, WAL truncated; ingest3 (seq 3) landed after.
    snap = json.loads((tmp_path / "snapshot.json").read_text())
    assert snap["digest"] == "d" and snap["watermark"] == 3
    records = [
        json.loads(line)
        for line in (tmp_path / "wal.jsonl").read_text().splitlines()
    ]
    assert [r["seq"] for r in records] == [3]
    assert jr.stats()["since_snapshot"] == 1
    jr.close()


def test_digest_mismatch_starts_fresh(tmp_path):
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path), snapshot_every=2)
    jr.open("old-digest", buf, drift)
    rng = np.random.default_rng(3)
    for _ in range(4):
        _ingest(jr, buf, drift, _chunk(rng))
    jr.close()

    buf2, drift2 = _fresh(model)
    jr2 = StreamJournal(str(tmp_path), snapshot_every=2)
    info = jr2.open("NEW-digest", buf2, drift2)
    assert info["stale_discarded"] is True
    assert info["records"] == 0 and info["rows"] == 0
    assert buf2.stats()["rows_seen"] == 0  # nothing stale replayed
    # The journal is now keyed to the new digest and usable.
    _ingest(jr2, buf2, drift2, _chunk(rng))
    assert buf2.stats()["rows_seen"] == 16
    jr2.close()


def test_stale_wal_without_snapshot_starts_fresh(tmp_path):
    (tmp_path / "wal.jsonl").write_text(
        json.dumps({"seq": 0, "kind": "begin", "digest": "other"}) + "\n"
    )
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path))
    info = jr.open("mine", buf, drift)
    assert info["stale_discarded"] is True and info["records"] == 0
    jr.close()


def test_torn_final_line_dropped(tmp_path):
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path), snapshot_every=100)
    jr.open("d", buf, drift)
    rng = np.random.default_rng(4)
    for _ in range(3):
        _ingest(jr, buf, drift, _chunk(rng))
    jr.close()
    ref_state = buf.state_dict()

    # Simulate the one half-flushed write a crash can leave.
    wal_path = tmp_path / "wal.jsonl"
    torn = wal_path.read_text() + '{"seq": 4, "kind": "ingest", "points": [[1.'
    wal_path.write_text(torn)

    buf2, drift2 = _fresh(model)
    jr2 = StreamJournal(str(tmp_path), snapshot_every=100)
    info = jr2.open("d", buf2, drift2)
    assert info["torn_tail_dropped"] is True
    assert info["records"] == 3
    # Only the torn (never-acked) record is lost; acked state is intact.
    # (rng_state differs is impossible: same absorb sequence.)
    assert buf2.state_dict() == ref_state
    jr2.close()


def test_torn_middle_line_raises(tmp_path):
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path), snapshot_every=100)
    jr.open("d", buf, drift)
    rng = np.random.default_rng(5)
    for _ in range(2):
        _ingest(jr, buf, drift, _chunk(rng))
    jr.close()

    wal_path = tmp_path / "wal.jsonl"
    lines = wal_path.read_text().splitlines()
    lines[1] = lines[1][: len(lines[1]) // 2]  # corrupt a MIDDLE record
    wal_path.write_text("\n".join(lines) + "\n")

    buf2, drift2 = _fresh(model)
    jr2 = StreamJournal(str(tmp_path), snapshot_every=100)
    with pytest.raises(ValueError, match="corrupt WAL record"):
        jr2.open("d", buf2, drift2)


def test_seq_gap_raises(tmp_path):
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path), snapshot_every=100)
    jr.open("d", buf, drift)
    rng = np.random.default_rng(6)
    for _ in range(3):
        _ingest(jr, buf, drift, _chunk(rng))
    jr.close()

    wal_path = tmp_path / "wal.jsonl"
    lines = wal_path.read_text().splitlines()
    del lines[2]  # drop a middle record -> seq gap
    wal_path.write_text("\n".join(lines) + "\n")

    buf2, drift2 = _fresh(model)
    jr2 = StreamJournal(str(tmp_path), snapshot_every=100)
    with pytest.raises(ValueError, match="WAL seq gap"):
        jr2.open("d", buf2, drift2)


def test_restart_rekeys_after_swap(tmp_path):
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path), snapshot_every=100)
    jr.open("gen1", buf, drift)
    rng = np.random.default_rng(7)
    for _ in range(3):
        _ingest(jr, buf, drift, _chunk(rng))
    assert jr.stats()["seq"] == 4

    jr.restart("gen2")
    assert jr.stats()["seq"] == 1  # fresh begin record only
    assert not os.path.exists(tmp_path / "snapshot.json")
    records = [
        json.loads(line)
        for line in (tmp_path / "wal.jsonl").read_text().splitlines()
    ]
    assert records == [{"seq": 0, "kind": "begin", "digest": "gen2"}]
    jr.close()


def test_trace_events_pass_check_trace(tmp_path):
    from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
    from scripts import check_trace

    trace = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path / "wal"), snapshot_every=3, tracer=tracer)
    jr.open("d", buf, drift)
    rng = np.random.default_rng(8)
    for _ in range(5):
        _ingest(jr, buf, drift, _chunk(rng))
    jr.restart("d2")  # wal_seq resets to 0 with a begin record
    _ingest(jr, buf, drift, _chunk(rng))
    jr.close()
    tracer.close()

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    appends = [e for e in events if e["stage"] == "wal_append"]
    assert [a["kind"] for a in appends][0] == "begin"
    recovers = [e for e in events if e["stage"] == "wal_recover"]
    assert len(recovers) == 1 and recovers[0]["records"] == 0

    # Contiguity check actually bites: forge a gap and expect a violation.
    with open(trace, "a", encoding="utf-8") as f:
        ev = dict(appends[-1])
        ev["wal_seq"] += 7
        ev["seq"] = 10_000
        f.write(json.dumps(ev) + "\n")
    _, errors = check_trace.validate_trace(trace)
    assert any("not contiguous" in e for e in errors)


def test_wrapped_reservoir_recovery_bitwise(tmp_path):
    """The recovery guarantee holds for a WRAPPED reservoir (n >> capacity,
    see the wal.py module docstring): once algorithm R starts replacing
    rows, every replacement decision consumes reservoir RNG state, so a
    replay that diverged by even one draw would produce a visibly different
    reservoir. reservoir_size=8 with 20 chunks x 16 rows = 320 candidate
    rows wraps the reservoir ~40x over."""
    model = _model()
    rng = np.random.default_rng(11)
    chunks = [_chunk(rng) for _ in range(20)]

    def fresh():
        buf = IngestBuffer(model, reservoir_size=8, seed=3)
        r = np.random.default_rng(103)
        drift = DriftDetector(r.uniform(0, 1, 512), r.integers(-1, 3, 512))
        return buf, drift

    ref_buf, ref_drift = fresh()
    for c in chunks:
        _ingest(None, ref_buf, ref_drift, c)
    # Sanity: the reservoir really wrapped — it saw far more rows than it
    # can hold, so replacement sampling ran.
    state = ref_buf.state_dict()
    assert state["rows_seen"] > 8 * 10 and len(state["reservoir"]) == 8

    buf_a, drift_a = fresh()
    jr_a = StreamJournal(str(tmp_path), snapshot_every=5)
    jr_a.open("digest-w", buf_a, drift_a)
    for c in chunks[:13]:  # crash mid-stream, reservoir already wrapped
        _ingest(jr_a, buf_a, drift_a, c)

    buf_b, drift_b = fresh()
    jr_b = StreamJournal(str(tmp_path), snapshot_every=5)
    info = jr_b.open("digest-w", buf_b, drift_b)
    assert info["snapshot"] is True
    for c in chunks[13:]:
        _ingest(jr_b, buf_b, drift_b, c)

    # Bitwise: contents AND RNG state, so all FUTURE absorbs agree too.
    assert buf_b.state_dict() == ref_buf.state_dict()
    assert drift_b.state_dict() == ref_drift.state_dict()
    np.testing.assert_array_equal(
        buf_b.refit_points(originals=16, seed=5),
        ref_buf.refit_points(originals=16, seed=5),
    )
    jr_a.close()
    jr_b.close()


def test_wrapped_reservoir_snapshot_only_recovery(tmp_path):
    """Same guarantee when recovery restores PURELY from the snapshot (no
    WAL tail to replay): the snapshot's serialized RNG state alone must
    resume the wrapped reservoir bitwise."""
    model = _model()
    rng = np.random.default_rng(12)
    chunks = [_chunk(rng) for _ in range(12)]

    def fresh():
        buf = IngestBuffer(model, reservoir_size=8, seed=4)
        r = np.random.default_rng(104)
        drift = DriftDetector(r.uniform(0, 1, 512), r.integers(-1, 3, 512))
        return buf, drift

    ref_buf, ref_drift = fresh()
    for c in chunks:
        _ingest(None, ref_buf, ref_drift, c)

    buf_a, drift_a = fresh()
    jr_a = StreamJournal(str(tmp_path), snapshot_every=10_000)
    jr_a.open("digest-s", buf_a, drift_a)
    for c in chunks[:9]:
        _ingest(jr_a, buf_a, drift_a, c)
    jr_a.snapshot(buf_a, drift_a)  # crash lands exactly on the snapshot

    buf_b, drift_b = fresh()
    jr_b = StreamJournal(str(tmp_path), snapshot_every=10_000)
    info = jr_b.open("digest-s", buf_b, drift_b)
    assert info["snapshot"] is True and info["records"] == 0
    for c in chunks[9:]:
        _ingest(jr_b, buf_b, drift_b, c)

    assert buf_b.state_dict() == ref_buf.state_dict()
    assert drift_b.state_dict() == ref_drift.state_dict()
    jr_a.close()
    jr_b.close()


def test_maintain_watermark_roundtrip(tmp_path):
    """The optional incremental-maintenance watermark survives the snapshot
    round trip verbatim, and recovery without a snapshot reports None."""
    model = _model()
    buf, drift = _fresh(model)
    jr = StreamJournal(str(tmp_path), snapshot_every=10_000)
    jr.open("digest-m", buf, drift)
    rng = np.random.default_rng(13)
    _ingest(jr, buf, drift, _chunk(rng))
    watermark = {
        "n": 80, "inserts": 16, "splices": 2, "spliced_edges": 18,
        "evicted_edges": 3, "pending_edges": 0, "journal_len": 18,
        "journal_sha": "ab" * 32, "mst_sha": "cd" * 32,
    }
    jr.snapshot(buf, drift, maintain=watermark)

    buf_b, drift_b = _fresh(model)
    jr_b = StreamJournal(str(tmp_path), snapshot_every=10_000)
    info = jr_b.open("digest-m", buf_b, drift_b)
    assert info["snapshot"] is True
    assert info["maintain"] == watermark
    jr.close()
    jr_b.close()

    # No snapshot -> no watermark.
    buf_c, drift_c = _fresh(model)
    jr_c = StreamJournal(str(tmp_path / "fresh"), snapshot_every=10_000)
    info = jr_c.open("digest-m", buf_c, drift_c)
    assert info["maintain"] is None
    jr_c.close()


def test_validation():
    with pytest.raises(ValueError):
        StreamJournal("/tmp/x-never-created", snapshot_every=0)
