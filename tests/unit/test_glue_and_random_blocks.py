"""Tests for the exact glue harvest, random-blocks merge, and refinement."""

import numpy as np
import pytest

from hdbscan_tpu.config import HDBSCANParams
from hdbscan_tpu.core.distances import rowwise_distance_np
from hdbscan_tpu.models import exact, hdbscan, mr_hdbscan
from hdbscan_tpu.ops.tiled import boruvka_glue_edges
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from tests.conftest import make_blobs


class TestRowwiseDistance:
    def test_matches_device_kernels(self, rng):
        import jax.numpy as jnp

        from hdbscan_tpu.core.distances import METRICS, pairwise_distance

        a = rng.normal(size=(10, 4))
        b = rng.normal(size=(10, 4))
        for metric in METRICS:
            want = np.diag(np.asarray(pairwise_distance(jnp.asarray(a), jnp.asarray(b), metric)))
            got = rowwise_distance_np(a, b, metric)
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-9)

    def test_unknown_metric_raises(self):
        with pytest.raises(ValueError):
            rowwise_distance_np(np.zeros((1, 2)), np.zeros((1, 2)), "nope")


class TestBoruvkaGlueEdges:
    def test_glue_edges_are_mst_edges(self, rng):
        """Glue edges + per-group MSTs must reproduce the exact MST weight."""
        pts, _ = make_blobs(rng, n=300, d=2, centers=3)
        groups = rng.integers(0, 5, size=300)  # arbitrary partition
        u, v, w = boruvka_glue_edges(pts, groups, "euclidean")
        assert len(u) == 4  # 5 groups -> 4 connectors to connectivity
        # every glue edge must be a true MST edge of the full point set
        # (cut property); check weights appear in the exact MST edge multiset.
        from tests.oracle.oracle_hdbscan import prim_mst

        _, _, mst_w = prim_mst(pts, np.zeros(300), self_edges=False)
        # the scanner carries float32 weights: compare at f32 precision
        for wi in w:
            assert np.any(np.isclose(mst_w, wi, rtol=1e-4)), wi

    def test_single_group_returns_empty(self, rng):
        pts, _ = make_blobs(rng, n=50, d=2, centers=1)
        u, v, w = boruvka_glue_edges(pts, np.zeros(50, np.int64), "euclidean")
        assert len(u) == len(v) == len(w) == 0

    def test_with_core_distances_uses_mrd(self, rng):
        pts, _ = make_blobs(rng, n=100, d=2, centers=2)
        core = np.full(100, 10.0)  # huge cores dominate every distance
        u, v, w = boruvka_glue_edges(pts, (np.arange(100) % 2), "euclidean", core=core)
        assert np.all(w >= 10.0)


class TestRandomBlocksMerge:
    def test_matches_exact_labels_on_blobs(self, rng):
        pts, truth = make_blobs(rng, n=800, d=3, centers=4, spread=0.08)
        params = HDBSCANParams(min_points=5, min_cluster_size=10)
        ex = hdbscan.fit(pts, params)
        u, v, w, core = exact.mst_edges_random_blocks(pts, 5, n_parts=6, seed=0)
        from hdbscan_tpu.core import tree as tree_mod

        _, labels = tree_mod.extract_clusters(len(pts), u, v, w, 10, self_levels=core)
        ari = adjusted_rand_index(labels, ex.labels)
        assert ari > 0.99, f"random-blocks merge ARI vs exact too low: {ari}"

    def test_spanning_tree_weight_is_exact(self, rng):
        pts, _ = make_blobs(rng, n=300, d=2, centers=2)
        u, v, w, core = exact.mst_edges_random_blocks(pts, 4, n_parts=4, seed=1)
        assert len(u) == 299  # spanning tree
        # global core distances must match the dense exact kernel
        import jax.numpy as jnp

        from hdbscan_tpu.core.knn import core_distances

        want = np.asarray(core_distances(jnp.asarray(pts), 4))
        np.testing.assert_allclose(core, want, rtol=1e-4)  # f32 scan precision
        # union-of-pair-block MSTs must reproduce the exact MST weight
        from tests.oracle.oracle_hdbscan import prim_mst

        _, _, ew = prim_mst(pts, want, self_edges=False)
        np.testing.assert_allclose(w.sum(), ew.sum(), rtol=1e-4)


class TestRefinement:
    def test_refinement_recovers_exact_macrostructure(self, rng):
        """A blob larger than capacity is chopped; refinement must pull the
        flat cut back toward the exact tree."""
        pts = np.concatenate(
            [rng.normal(size=(600, 2)) * 0.2, rng.normal(size=(200, 2)) * 0.2 + 4.0]
        )
        params = HDBSCANParams(
            min_points=5, min_cluster_size=50, processing_units=150, k=0.1, seed=3
        )
        ex = hdbscan.fit(pts, params.replace(processing_units=1000))
        no_refine = mr_hdbscan.fit(pts, params.replace(refine_iterations=0))
        refined = mr_hdbscan.fit(pts, params)
        ari_no = adjusted_rand_index(no_refine.labels, ex.labels)
        ari_yes = adjusted_rand_index(refined.labels, ex.labels)
        assert ari_yes >= ari_no - 1e-9
        assert ari_yes > 0.9, f"refined ARI vs exact too low: {ari_yes}"


class TestKnnIndices:
    def test_return_indices_matches_brute_force(self, rng):
        from hdbscan_tpu.ops.tiled import knn_core_distances

        pts = rng.normal(size=(300, 3))
        core, knn, idx = knn_core_distances(pts, 5, k=4, return_indices=True)
        d = np.sqrt(((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1))
        for i in range(0, 300, 37):
            got = np.sort(d[i][idx[i]])
            np.testing.assert_allclose(got, knn[i], rtol=1e-5, atol=1e-7)
        # distinct random points: the unique zero-distance column is self
        assert np.all(idx[:, 0] == np.arange(300))
