"""Flat-cut-level refinement (config.refine_flat_iterations, r5).

The loop harvests exact min MRD edges across the flat partition (noise as
singletons) and rebuilds until the labels fix — repairing pool
incompleteness at the top of the tree, the measured source of cross-draw
flat-cut spread (seed_sweep45_skin_r5.jsonl: 45 Skin draws all converge to
the exact tree's reading, std 0.0000).
"""

import numpy as np

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import mr_hdbscan
from hdbscan_tpu.utils.evaluation import adjusted_rand_index


def _lattice(seed=0):
    """Integer-lattice clusters (the tie structure that spreads draws)."""
    rng = np.random.default_rng(seed)
    centers = np.array([[0, 0, 0], [30, 0, 0], [0, 30, 0], [30, 30, 0]])
    return np.concatenate(
        [c + rng.integers(-8, 9, size=(900, 3)) for c in centers]
    ).astype(np.float64)


BASE = dict(
    min_points=6,
    min_cluster_size=150,
    processing_units=512,
    k=0.05,
    dedup_points=True,
)


class TestRefineFlat:
    def test_draws_converge_to_one_reading(self):
        data = _lattice()
        labs = []
        for seed in (0, 1, 2):
            p = HDBSCANParams(**BASE, seed=seed, refine_flat_iterations=8)
            labs.append(mr_hdbscan.fit(data, p).labels)
        for other in labs[1:]:
            assert (
                adjusted_rand_index(labs[0], other, noise_as_singletons=True)
                == 1.0
            ), "dbflat draws disagree"

    def test_trace_event_and_early_stop(self):
        from hdbscan_tpu.utils.tracing import Tracer

        data = _lattice(3)
        tracer = Tracer(stream=None)
        p = HDBSCANParams(**BASE, seed=0, refine_flat_iterations=8)
        mr_hdbscan.fit(data, p, trace=tracer)
        evs = [e for e in tracer.events if e.name == "refine_flat"]
        assert evs, "no refine_flat trace event"
        # Early stop: far fewer passes than the budget once labels fix.
        assert len(evs) <= 8
        assert evs[-1].fields["changed"] == 0 or len(evs) == 8

    def test_convergence_is_relabel_invariant(self):
        """A pure cluster renumbering between passes must read as converged
        (r6 satellite: the old ``labels != prev`` test kept iterating on
        permuted-but-identical partitions until the budget ran out)."""
        f = mr_hdbscan._same_flat_partition
        a = np.array([0, 1, 1, 2, 2, 2, 0])
        # Renumbered (1<->2 swapped): same partition.
        assert f(a, np.array([0, 2, 2, 1, 1, 1, 0]))
        assert f(a, a)
        # A genuine membership move is NOT converged.
        assert not f(a, np.array([0, 1, 2, 2, 2, 2, 0]))
        # Noise (label 0) is pinned, not a renumberable cluster: a point
        # flipping between noise and a cluster changes the partition.
        assert not f(a, np.array([1, 1, 1, 2, 2, 2, 0]))
        # Two clusters merging into one is not a bijection.
        assert not f(a, np.array([0, 1, 1, 1, 1, 1, 0]))
        # All-noise vs all-noise trivially converged.
        z = np.zeros(4, np.int64)
        assert f(z, z.copy())

    def test_zero_iterations_is_default_noop(self):
        data = _lattice(5)
        p0 = HDBSCANParams(**BASE, seed=0)
        p1 = HDBSCANParams(**BASE, seed=0, refine_flat_iterations=0)
        r0 = mr_hdbscan.fit(data, p0)
        r1 = mr_hdbscan.fit(data, p1)
        np.testing.assert_array_equal(r0.labels, r1.labels)
