"""The stdlib metrics registry (``utils/metrics.py``) and the Tracer ring
buffer (``utils/tracing.py``, README "Observability").

Pins the contracts the serving path and the validators rely on:

- ``Histogram.quantile`` is nearest-rank within one bucket width of
  ``np.percentile(..., method="inverted_cdf")`` over the raw samples —
  including the empty/single-sample/overflow-bucket edges;
- ``MetricsRegistry.render`` emits text that ``scripts/check_metrics.py``
  parses with ZERO errors, and two successive renders with traffic in
  between pass its counter-monotonicity check;
- concurrent mutation from many threads (the HTTP handler pool, the
  batcher worker, and the refitter all share one registry in the server)
  loses no increments and never corrupts a mid-mutation render;
- ``merge`` folds a second registry's state in exactly;
- a bounded ``Tracer`` caps ``events`` at ``max_events``, counts the
  drops, keeps every event flowing to sinks, and says so in ``summary``.
"""

import threading

import numpy as np
import pytest

from hdbscan_tpu.utils.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    log_buckets,
)
from scripts import check_metrics


def _raw_nearest_rank(vals, q):
    return float(np.percentile(np.asarray(vals), q * 100.0,
                               method="inverted_cdf"))


def _bucket_width_at(edges, value):
    import bisect

    i = bisect.bisect_left(edges, value)
    if i >= len(edges):
        return float("inf")
    return edges[i] - (edges[i - 1] if i > 0 else 0.0)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("n", [1, 7, 100, 5000])
def test_histogram_quantile_within_one_bucket_of_nearest_rank(seed, n):
    rng = np.random.default_rng(seed)
    # log-normal-ish latencies spanning several decades of the bucket grid
    vals = rng.exponential(0.005, n) + rng.exponential(0.05, n) * (
        rng.random(n) < 0.1
    )
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", "t", buckets=DEFAULT_LATENCY_BUCKETS)
    for v in vals:
        h.observe(float(v))
    assert h.count() == n
    assert h.total() == pytest.approx(float(vals.sum()))
    for q in (0.5, 0.9, 0.99, 0.999, 1.0):
        raw = _raw_nearest_rank(vals, q)
        got = h.quantile(q)
        width = _bucket_width_at(h.buckets, raw)
        assert abs(got - raw) <= width, (q, got, raw, width)


def test_histogram_quantile_edges():
    reg = MetricsRegistry()
    h = reg.histogram("t_edge_seconds", "t", buckets=[0.1, 1.0])
    # empty histogram has no quantiles
    assert h.quantile(0.99) is None
    # single sample: every quantile is that sample's bucket edge
    h.observe(0.05)
    assert h.quantile(0.5) == h.quantile(0.999) == 0.1
    # overflow: samples beyond the last edge land in +Inf; the quantile
    # answers with the max observed value, not infinity
    h.observe(25.0)
    h.observe(50.0)
    assert h.quantile(1.0) == 50.0
    with pytest.raises(ValueError):
        h.quantile(0.0)
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_render_is_valid_exposition_and_monotonic():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "reqs", labelnames=("route", "status"))
    g = reg.gauge("t_in_flight", "inflight")
    h = reg.histogram("t_latency_seconds", "lat", labelnames=("route",))
    c.inc(route="/predict", status="200")
    c.inc(2, route="/metrics", status="200")
    g.set(3.0)
    for v in (0.001, 0.02, 0.5):
        h.observe(v, route="/predict")
    parsed1, errors1 = check_metrics.validate_exposition(reg.render(), "r1")
    assert errors1 == []
    # traffic between scrapes; the gauge may DECREASE without violating
    # monotonicity (check_metrics exempts gauges)
    c.inc(route="/predict", status="200")
    h.observe(0.2, route="/predict")
    g.set(0.0)
    parsed2, errors2 = check_metrics.validate_exposition(reg.render(), "r2")
    assert errors2 == []
    assert check_metrics.check_monotonic(parsed1, parsed2) == []
    # a decreasing counter IS flagged
    shrunk = dict(parsed2["samples"])
    key = ("t_requests_total", (("route", "/predict"), ("status", "200")))
    shrunk[key] = 0.0
    bad = {**parsed2, "samples": shrunk}
    assert check_metrics.check_monotonic(parsed1, bad)


def test_label_escaping_round_trips_through_validator():
    reg = MetricsRegistry()
    c = reg.counter("t_weird_total", "w", labelnames=("path",))
    nasty = 'a"b\\c\nd'
    c.inc(path=nasty)
    parsed, errors = check_metrics.validate_exposition(reg.render(), "r")
    assert errors == []
    (key,) = [k for k in parsed["samples"] if k[0] == "t_weird_total"]
    assert dict(key[1])["path"] == nasty


def test_registry_rejects_type_and_name_conflicts():
    reg = MetricsRegistry()
    c = reg.counter("t_thing_total", "t")
    # same name + type + labels: the same object comes back
    assert reg.counter("t_thing_total", "t") is c
    with pytest.raises(ValueError):
        reg.gauge("t_thing_total", "t")
    with pytest.raises(ValueError):
        reg.counter("t_thing_total", "t", labelnames=("route",))
    with pytest.raises(ValueError):
        reg.counter("9bad", "t")
    with pytest.raises(ValueError):
        reg.histogram("t_h_seconds", "t", buckets=[2.0, 1.0])
    with pytest.raises(ValueError):
        c.inc(-1.0)


def test_concurrent_mutation_loses_nothing():
    """HTTP pool + batcher + refitter all hammer one registry: counter
    totals must be exact and a render taken mid-mutation must stay
    parseable."""
    reg = MetricsRegistry()
    c = reg.counter("t_hits_total", "hits", labelnames=("route",))
    g = reg.gauge("t_busy", "busy")
    h = reg.histogram("t_work_seconds", "work")
    n_threads, per_thread = 8, 500
    render_errors = []
    barrier = threading.Barrier(n_threads + 1)

    def worker(i):
        route = f"/r{i % 3}"
        barrier.wait()
        for j in range(per_thread):
            c.inc(route=route)
            g.inc()
            h.observe(0.001 * (j % 7 + 1))
            g.dec()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    barrier.wait()
    for _ in range(20):  # scrape while the traffic is in flight
        _, errs = check_metrics.validate_exposition(reg.render(), "live")
        render_errors += errs
    for t in threads:
        t.join()
    assert render_errors == []
    assert sum(int(v) for _, v in c.samples()) == n_threads * per_thread
    assert h.count() == n_threads * per_thread
    assert g.value() == 0.0
    _, errs = check_metrics.validate_exposition(reg.render(), "final")
    assert errs == []


def test_merge_folds_state_exactly():
    a, b = MetricsRegistry(), MetricsRegistry()
    edges = log_buckets(0.001, 2.0, 8)
    ca = a.counter("t_n_total", "n", labelnames=("k",))
    cb = b.counter("t_n_total", "n", labelnames=("k",))
    ha = a.histogram("t_w_seconds", "w", buckets=edges)
    hb = b.histogram("t_w_seconds", "w", buckets=edges)
    ca.inc(3, k="x")
    cb.inc(4, k="x")
    cb.inc(1, k="y")
    for v in (0.002, 0.01):
        ha.observe(v)
    for v in (0.004, 0.5):
        hb.observe(v)
    ca.merge(cb)
    ha.merge(hb)
    assert ca.value(k="x") == 7.0 and ca.value(k="y") == 1.0
    assert ha.count() == 4
    assert ha.total() == pytest.approx(0.002 + 0.01 + 0.004 + 0.5)
    mismatched = a.histogram("t_other_seconds", "o", buckets=[1.0, 2.0])
    with pytest.raises(ValueError):
        ha.merge(mismatched)


def _replica_registry(rid, routes):
    """A registry shaped like one serve replica's /metrics: request
    counters over ``routes``, an in-flight gauge, a latency histogram."""
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "reqs", labelnames=("route", "status"))
    g = reg.gauge("t_in_flight", "inflight")
    h = reg.histogram("t_latency_seconds", "lat", labelnames=("route",),
                      buckets=DEFAULT_LATENCY_BUCKETS)
    for i, route in enumerate(routes):
        c.inc(10 * (int(rid) + 1) + i, route=route, status="200")
        for v in (0.001 * (int(rid) + 1), 0.02, 0.3):
            h.observe(v, route=route)
    g.set(float(rid))
    return reg


def test_fleet_merge_n_registries_overlapping_and_disjoint_labels():
    """The router's aggregation path: scrape N replicas, re-parse each
    with a ``replica`` static label, fold into one registry. Replicas 0/1
    overlap on /predict (disambiguated only by the replica label) while
    replica 2 brings a disjoint /swap series; nothing collides, nothing
    is lost, and the aggregate render is a valid exposition."""
    from hdbscan_tpu.utils.metrics import registry_from_exposition

    routes = {"0": ("/predict", "/ingest"), "1": ("/predict",),
              "2": ("/swap",)}
    agg = MetricsRegistry()
    agg.counter("t_requests_total", "reqs",
                labelnames=("replica", "route", "status")).inc(
        1, replica="router", route="/predict", status="503")
    for rid, rroutes in routes.items():
        scrape = _replica_registry(rid, rroutes).render()
        agg.merge(registry_from_exposition(scrape, {"replica": rid}))
    c = agg.get("t_requests_total")
    assert c.value(replica="0", route="/predict", status="200") == 10.0
    assert c.value(replica="1", route="/predict", status="200") == 20.0
    assert c.value(replica="2", route="/swap", status="200") == 30.0
    assert c.value(replica="0", route="/ingest", status="200") == 11.0
    # the router's own pre-existing series survives the merges
    assert c.value(replica="router", route="/predict", status="503") == 1.0
    g = agg.get("t_in_flight")
    assert [g.value(replica=r) for r in ("0", "1", "2")] == [0.0, 1.0, 2.0]
    parsed, errors = check_metrics.validate_exposition(agg.render(), "agg")
    assert errors == []
    # every replica-origin series carries the replica label
    for (name, labels), _ in parsed["samples"].items():
        assert dict(labels).get("replica"), (name, labels)


def test_fleet_merge_histogram_state_exact():
    """Histogram folding is exact at bucket resolution: cumulative bucket
    counts, _sum, and _count of the aggregate equal the element-wise sums
    of the replicas' — through the text round-trip the router uses."""
    from hdbscan_tpu.utils.metrics import registry_from_exposition

    regs = {rid: _replica_registry(rid, ("/predict",)) for rid in "012"}
    agg = MetricsRegistry()
    for rid, reg in regs.items():
        agg.merge(registry_from_exposition(reg.render(), {"replica": rid}))
    h = agg.get("t_latency_seconds")
    for rid, reg in regs.items():
        src = reg.get("t_latency_seconds")
        assert h.count(replica=rid, route="/predict") == src.count(
            route="/predict")
        assert h.total(replica=rid, route="/predict") == pytest.approx(
            src.total(route="/predict"))
    # fold the per-replica series once more into a replica-less registry:
    # overlapping label sets now MERGE instead of sitting side by side
    flat_a = registry_from_exposition(regs["0"].render())
    flat_a.merge(registry_from_exposition(regs["1"].render()))
    fh = flat_a.get("t_latency_seconds")
    assert fh.count(route="/predict") == 6
    assert fh.total(route="/predict") == pytest.approx(
        (0.001 + 0.02 + 0.3) + (0.002 + 0.02 + 0.3))
    parsed, errors = check_metrics.validate_exposition(flat_a.render(), "flat")
    assert errors == []
    # cumulative bucket counts are the element-wise sum of the sources
    buckets = {
        dict(labels)["le"]: v
        for (name, labels), v in parsed["samples"].items()
        if name == "t_latency_seconds_bucket"
    }
    for le, v in buckets.items():
        want = sum(
            sum(1 for x in obs if x <= float(le))
            for obs in ((0.001, 0.02, 0.3), (0.002, 0.02, 0.3))
        )
        assert v == want, (le, v, want)


def test_registry_from_exposition_round_trips_render():
    """parse(render(reg)) reproduces every sample value — the property
    that makes scrape-text a faithful transport between processes."""
    from hdbscan_tpu.utils.metrics import registry_from_exposition

    reg = _replica_registry("0", ("/predict", "/ingest"))
    parsed_src, errs_src = check_metrics.validate_exposition(
        reg.render(), "src")
    back = registry_from_exposition(reg.render())
    parsed_back, errs_back = check_metrics.validate_exposition(
        back.render(), "back")
    assert errs_src == errs_back == []
    assert parsed_back["samples"] == parsed_src["samples"]
    # garbage fails loudly, not silently smaller
    with pytest.raises(ValueError, match="unparseable"):
        registry_from_exposition("t_requests_total{oops\n")
    with pytest.raises(ValueError, match="TYPE"):
        registry_from_exposition("no_type_line 1\n")


def test_scrape_during_merge_stays_parseable():
    """A /metrics scrape racing the router's aggregation merge must always
    see a parseable exposition — merge() and render() share the registry
    lock discipline."""
    from hdbscan_tpu.utils.metrics import registry_from_exposition

    scrapes = [
        _replica_registry(str(i % 3), ("/predict",)).render()
        for i in range(12)
    ]
    agg = MetricsRegistry()
    errors, stop = [], threading.Event()

    def scraper():
        while not stop.is_set():
            _, errs = check_metrics.validate_exposition(agg.render(), "live")
            errors.extend(errs)

    threads = [threading.Thread(target=scraper) for _ in range(3)]
    for t in threads:
        t.start()
    for i, text in enumerate(scrapes * 5):
        agg.merge(registry_from_exposition(text, {"replica": str(i)}))
    stop.set()
    for t in threads:
        t.join()
    assert errors == []
    _, errs = check_metrics.validate_exposition(agg.render(), "final")
    assert errs == []


def test_tracer_ring_buffer_bounds_memory_not_sinks():
    from hdbscan_tpu.utils.tracing import Tracer

    sunk = []

    class ListSink:
        def emit(self, event):
            sunk.append(event)

        def close(self):
            pass

    t = Tracer(sinks=[ListSink()], max_events=100)
    for i in range(1000):
        t("stage_a", wall_s=0.001, i=i)
    # in-memory window bounded; drops counted; the sink saw everything
    assert len(t.events) <= 100
    assert t.events_dropped == 1000 - len(t.events)
    assert len(sunk) == 1000
    # the retained window is the NEWEST events
    assert t.events[-1].fields["i"] == 999
    assert "ring buffer" in t.summary() and "dropped" in t.summary()
    # unbounded default: nothing dropped, no note
    t2 = Tracer()
    for i in range(200):
        t2("stage_a", wall_s=0.001)
    assert len(t2.events) == 200 and t2.events_dropped == 0
    assert "ring buffer" not in t2.summary()
    with pytest.raises(ValueError):
        Tracer(max_events=-5)
