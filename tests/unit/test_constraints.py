"""Constraint subsystem tests: parsing, per-cluster credits, EOM influence."""

import numpy as np
import pytest

from hdbscan_tpu.config import HDBSCANParams
from hdbscan_tpu.core.constraints import (
    Constraint,
    count_constraints_satisfied,
    load_constraints,
)
from hdbscan_tpu.core import tree as tree_mod
from hdbscan_tpu.models import hdbscan
from tests.conftest import make_blobs


def two_cluster_tree(rng):
    pts, truth = make_blobs(rng, n=80, d=2, centers=2, spread=0.05)
    res = hdbscan.fit(pts, HDBSCANParams(min_points=4, min_cluster_size=5))
    return pts, truth, res


class TestLoad:
    def test_parse(self, tmp_path):
        p = tmp_path / "c.csv"
        p.write_text("0,5,ml\n2,7,cl\n\n 3 , 4 , ML \n")
        cons = load_constraints(str(p))
        assert cons == [
            Constraint(0, 5, "ml"),
            Constraint(2, 7, "cl"),
            Constraint(3, 4, "ml"),
        ]

    def test_bad_type(self, tmp_path):
        p = tmp_path / "c.csv"
        p.write_text("0,1,xx\n")
        with pytest.raises(ValueError):
            load_constraints(str(p))

    def test_bad_arity(self, tmp_path):
        p = tmp_path / "c.csv"
        p.write_text("0,1\n")
        with pytest.raises(ValueError):
            load_constraints(str(p))


class TestCounts:
    def test_must_link_same_cluster(self, rng):
        pts, truth, res = two_cluster_tree(rng)
        same = np.nonzero(res.labels == res.labels[np.argmax(res.labels > 0)])[0]
        a, b = int(same[0]), int(same[1])
        num, vnum = count_constraints_satisfied(res.tree, [Constraint(a, b, "ml")])
        # Their shared (selected) cluster earned the +2.
        assert num[res.labels[a]] == 2
        assert num.sum() >= 2  # deeper shared ancestors may earn too

    def test_must_link_across_clusters_no_credit(self, rng):
        pts, truth, res = two_cluster_tree(rng)
        labels_present = [l for l in np.unique(res.labels) if l > 0]
        a = int(np.nonzero(res.labels == labels_present[0])[0][0])
        b = int(np.nonzero(res.labels == labels_present[1])[0][0])
        num, _ = count_constraints_satisfied(res.tree, [Constraint(a, b, "ml")])
        # Only non-root common ancestors could earn; the two flat clusters
        # themselves earn nothing.
        assert num[res.labels[a]] == 0
        assert num[res.labels[b]] == 0

    def test_cannot_link_across_clusters(self, rng):
        pts, truth, res = two_cluster_tree(rng)
        labels_present = [l for l in np.unique(res.labels) if l > 0]
        a = int(np.nonzero(res.labels == labels_present[0])[0][0])
        b = int(np.nonzero(res.labels == labels_present[1])[0][0])
        num, _ = count_constraints_satisfied(res.tree, [Constraint(a, b, "cl")])
        assert num[res.labels[a]] >= 1
        assert num[res.labels[b]] >= 1

    def test_root_credits(self, rng):
        """Root earns +2 per must-link (reference pre-loop credit,
        HDBSCANStar.java:241-244) and nothing from cannot-links directly."""
        pts, truth, res = two_cluster_tree(rng)
        num, vnum = count_constraints_satisfied(
            res.tree, [Constraint(0, 1, "ml"), Constraint(0, 2, "cl")]
        )
        assert num[tree_mod.ROOT_LABEL] == 2

    def test_virtual_credit_requires_split(self, rng):
        """vGamma goes only to clusters that split (parents-of-new-clusters
        scoping); total virtual credit is bounded by cl endpoints."""
        pts, truth, res = two_cluster_tree(rng)
        cons = [Constraint(0, 1, "cl"), Constraint(2, 3, "cl")]
        num, vnum = count_constraints_satisfied(res.tree, cons)
        credited = np.nonzero(vnum)[0]
        assert np.all(res.tree.has_children[credited])
        assert vnum.sum() <= 2 * len(cons)

    def test_noise_endpoint_virtual_credit(self, rng):
        pts, truth, res = two_cluster_tree(rng)
        noise = np.nonzero(res.labels == 0)[0]
        if len(noise) == 0:
            pytest.skip("no noise points in this draw")
        a = int(noise[0])
        b = int(np.nonzero(res.labels > 0)[0][0])
        num, vnum = count_constraints_satisfied(res.tree, [Constraint(a, b, "cl")])
        assert vnum.sum() >= 1


class TestEndToEnd:
    def test_constraints_file_steers_extraction(self, rng, tmp_path):
        """A heavily-weighted must-link pair in one blob forces selection of a
        cluster containing both points' subtree."""
        pts, truth, res = two_cluster_tree(rng)
        same = np.nonzero(res.labels == res.labels[np.argmax(res.labels > 0)])[0]
        cons_file = tmp_path / "cons.csv"
        cons_file.write_text(f"{same[0]},{same[1]},ml\n")
        params = HDBSCANParams(
            min_points=4, min_cluster_size=5, constraints_file=str(cons_file)
        )
        res2 = hdbscan.fit(pts, params)
        # Constrained extraction still labels both endpoints together.
        assert res2.labels[same[0]] == res2.labels[same[1]] != 0


class TestVectorizedParity:
    """The LCA-vectorized counting must match a per-constraint ancestor-chain
    walk (the reference's HDBSCANStar.java:738-789 shape) exactly."""

    @staticmethod
    def _chain_walk_oracle(tree, constraints):
        c = tree.n_clusters
        chains = [set() for _ in range(c + 1)]
        for label in range(1, c + 1):
            par = int(tree.parent[label])
            chains[label] = {label} | (chains[par] if par > 0 else set())
        num = np.zeros(c + 1, np.int64)
        vnum = np.zeros(c + 1, np.int64)
        last = tree.point_last_cluster
        for con in constraints:
            ca = chains[int(last[con.point_a])]
            cb = chains[int(last[con.point_b])]
            if con.kind == "ml":
                for lbl in ca & cb:
                    num[lbl] += 2
            else:
                for lbl in ca ^ cb:
                    num[lbl] += 1
                for p in (con.point_a, con.point_b):
                    lbl = int(last[p])
                    if tree.has_children[lbl]:
                        vnum[lbl] += 1
        return num, vnum

    def test_random_fit_parity(self, rng):
        from tests.conftest import make_blobs

        from hdbscan_tpu import HDBSCANParams
        from hdbscan_tpu.models import hdbscan

        data, _ = make_blobs(rng, n=900, d=3, centers=7, spread=0.5)
        res = hdbscan.fit(data, HDBSCANParams(min_points=4, min_cluster_size=15))
        n = len(data)
        cons = [
            Constraint(int(a), int(b), "ml" if rng.random() < 0.5 else "cl")
            for a, b in rng.integers(0, n, size=(400, 2))
        ]
        got = count_constraints_satisfied(res.tree, cons)
        want = self._chain_walk_oracle(res.tree, cons)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])

    def test_million_constraints_fast(self, rng):
        # VERDICT item 9's bar: 1M constraints in seconds, not minutes.
        import time

        from tests.conftest import make_blobs

        from hdbscan_tpu import HDBSCANParams
        from hdbscan_tpu.models import hdbscan

        data, _ = make_blobs(rng, n=2000, d=3, centers=8, spread=0.4)
        res = hdbscan.fit(data, HDBSCANParams(min_points=4, min_cluster_size=25))
        pairs = rng.integers(0, len(data), size=(1_000_000, 2))
        kinds = rng.random(1_000_000) < 0.5
        cons = [
            Constraint(int(a), int(b), "ml" if k else "cl")
            for (a, b), k in zip(pairs, kinds)
        ]
        t0 = time.monotonic()
        num, vnum = count_constraints_satisfied(res.tree, cons)
        wall = time.monotonic() - t0
        assert wall < 20.0, f"1M constraints took {wall:.1f}s"
        assert num.sum() > 0
