"""Fleet control plane (``hdbscan_tpu/fleet/``, README "Fleet control
plane") — the autoscaler's decision discipline, the zero-copy artifact
store, and the fit-as-a-service scheduler, tested without real replicas:

- ``Autoscaler.decide`` is a pure hysteresis machine: ``up_after``
  consecutive hot ticks to grow, ``down_after`` idle ticks to shrink,
  any contrary tick resets the streak, and min/max bounds veto;
- ``Autoscaler.tick`` holds through the cooldown window and counts what
  it actually attempted against a fake router;
- ``ArtifactStore`` keys by content digest (two byte-identical artifacts
  share one mapping), returns the same object on re-hit, survives a
  corrupted spool by falling back to ``ClusterModel.load``, and emits
  the miss-then-hits ``artifact_map`` history ``check_trace.py`` pins;
- ``FitScheduler`` walks queued → running → published|failed exactly
  once per job, publishes through the caller's callback, sheds over-quota
  (429) and over-bound (503) submits, and isolates a failed fit from both
  serving and its worker thread;
- the router's scaling seams: ``signals()`` reflects in-flight and the
  latency window, ``_free_rid`` reuses the lowest departed rid (WAL
  continuity), and ``_replica_environ`` injects the persistent compile
  cache dir for warm standby spawns.
"""

import os
import threading
import time

import numpy as np
import pytest

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.fault.policy import ShedRequest
from hdbscan_tpu.fleet import ArtifactStore, Autoscaler, FitScheduler, FleetRouter
from hdbscan_tpu.fleet.artifacts import file_digest


class _ListTracer:
    def __init__(self):
        self.events = []

    def __call__(self, stage, **fields):
        self.events.append({"stage": stage, **fields})


# -- Autoscaler.decide ---------------------------------------------------------


@pytest.mark.parametrize(
    "kw",
    [
        {"min_replicas": 0},
        {"min_replicas": 3, "max_replicas": 2},
        {"high_load": 1.0, "low_load": 1.0},
        {"up_after": 0},
        {"interval_s": 0.0},
        {"cooldown_s": -1.0},
    ],
)
def test_autoscaler_rejects_bad_knobs(kw):
    with pytest.raises(ValueError):
        Autoscaler(router=None, **kw)


def _scaler(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("high_load", 4.0)
    kw.setdefault("low_load", 0.5)
    kw.setdefault("up_after", 2)
    kw.setdefault("down_after", 3)
    return Autoscaler(router=None, **kw)


def _sig(replicas=2, load=1.0, p99=None):
    out = {"replicas": replicas, "in_flight_per_up": load}
    if p99 is not None:
        out["p99_s"] = p99
    return out


def test_decide_needs_consecutive_hot_ticks():
    s = _scaler(up_after=3)
    assert s.decide(_sig(load=9.0)) is None
    assert s.decide(_sig(load=9.0)) is None
    assert s.decide(_sig(load=9.0)) == ("up", "queue_depth")
    # streak resets after firing
    assert s.decide(_sig(load=9.0)) is None


def test_decide_contrary_tick_resets_streak():
    s = _scaler(up_after=2)
    assert s.decide(_sig(load=9.0)) is None
    assert s.decide(_sig(load=1.0)) is None  # cool tick wipes the vote
    assert s.decide(_sig(load=9.0)) is None
    assert s.decide(_sig(load=9.0)) == ("up", "queue_depth")


def test_decide_down_is_slower_and_bounded():
    s = _scaler(down_after=3)
    assert s.decide(_sig(load=0.0)) is None
    assert s.decide(_sig(load=0.0)) is None
    assert s.decide(_sig(load=0.0)) == ("down", "idle")
    # at min_replicas the idle fleet never shrinks further
    for _ in range(10):
        assert s.decide(_sig(replicas=1, load=0.0)) is None


def test_decide_respects_max_replicas():
    s = _scaler(up_after=1)
    for _ in range(5):
        assert s.decide(_sig(replicas=4, load=9.0)) is None


def test_decide_p99_signal_votes_up_and_vetoes_down():
    s = _scaler(up_after=2, high_p99_s=0.2)
    assert s.decide(_sig(load=1.0, p99=0.5)) is None
    assert s.decide(_sig(load=1.0, p99=0.5)) == ("up", "p99")
    # a hot p99 vetoes the idle vote even when load is low
    s2 = _scaler(down_after=1, high_p99_s=0.2)
    assert s2.decide(_sig(load=0.0, p99=0.5)) is None  # up-vote instead
    s3 = _scaler(down_after=1, high_p99_s=0.0)  # latency signal disabled
    assert s3.decide(_sig(load=0.0, p99=0.5)) == ("down", "idle")


class _FakeScalingRouter:
    def __init__(self, replicas=2, load=9.0):
        self.replicas = list(range(replicas))
        self.load = load
        self.ups = 0
        self.downs = 0

    def signals(self):
        return {"replicas": len(self.replicas),
                "in_flight_per_up": self.load}

    def scale_up(self, reason="manual", timeout=None):
        self.ups += 1
        self.replicas.append(len(self.replicas))
        return str(len(self.replicas) - 1)

    def scale_down(self, rid=None, reason="manual", timeout=None):
        self.downs += 1
        self.replicas.pop()
        return True


def test_tick_scales_and_holds_through_cooldown():
    router = _FakeScalingRouter(replicas=2, load=9.0)
    s = Autoscaler(router, up_after=1, cooldown_s=30.0)
    assert s.tick(now=0.0) == ("up", "queue_depth")
    assert router.ups == 1 and s.scaled_up == 1
    # cooldown: the very next tick is a no-op even though load is hot
    assert s.tick(now=0.0) is None
    assert router.ups == 1
    # after the hold expires, ticks act again
    s._hold_until = 0.0
    assert s.tick(now=0.0) == ("up", "queue_depth")
    assert router.ups == 2


def test_tick_scales_down_idle_fleet():
    router = _FakeScalingRouter(replicas=3, load=0.0)
    s = Autoscaler(router, down_after=2, cooldown_s=0.0)
    assert s.tick(now=0.0) is None
    assert s.tick(now=0.0) == ("down", "idle")
    assert router.downs == 1 and s.scaled_down == 1


def test_autoscaler_loop_grows_to_min_replicas():
    router = _FakeScalingRouter(replicas=1, load=1.0)
    s = Autoscaler(router, min_replicas=3, interval_s=0.05)
    s.start()
    try:
        deadline = time.monotonic() + 5.0
        while len(router.replicas) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        s.stop()
    assert len(router.replicas) == 3
    assert s.stats()["scaled_up"] == 2
    assert s.stats()["running"] is False


# -- ArtifactStore -------------------------------------------------------------


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    """One real fit, saved twice (byte-identical) for digest-dedup tests."""
    from hdbscan_tpu.models import hdbscan
    from hdbscan_tpu.serve.artifact import ClusterModel

    rng = np.random.default_rng(7)
    data = np.vstack(
        [rng.normal(c, 0.2, size=(60, 3)) for c in (0.0, 3.0, 6.0)]
    )
    params = HDBSCANParams(min_points=8, min_cluster_size=8)
    result = hdbscan.fit(data, params)
    model = ClusterModel.from_fit_result(result, data, params)
    root = tmp_path_factory.mktemp("artifacts")
    a = model.save(str(root / "acme.npz"), compress=False)
    b = model.save(str(root / "globex.npz"), compress=False)
    return data, params, model, a, b


def test_store_dedups_byte_identical_artifacts(saved_model, tmp_path):
    data, _, _, a, b = saved_model
    assert file_digest(a) == file_digest(b)
    tracer = _ListTracer()
    store = ArtifactStore(spool_dir=str(tmp_path / "spool"), tracer=tracer)
    m1 = store.load(a)
    m2 = store.load(b)  # same digest, different path: shared entry
    m3 = store.load(a)
    assert m1 is m2 and m1 is m3
    np.testing.assert_allclose(np.asarray(m1.data), data)
    # the miss-then-hits contract check_trace.py pins
    hits = [e["hit"] for e in tracer.events if e["stage"] == "artifact_map"]
    assert hits == [False, True, True]
    assert tracer.events[0]["spooled"] is True
    stats = store.stats()
    assert stats["resident"] == 1 and stats["resident_bytes"] > 0
    assert stats["refs"][file_digest(a)] == 3


def test_store_mmaps_spooled_members(saved_model, tmp_path):
    *_, a, _ = saved_model
    store = ArtifactStore(spool_dir=str(tmp_path / "spool"))
    model = store.load(a)
    arr = np.asarray(model.data)
    assert isinstance(arr, np.memmap) or isinstance(arr.base, np.memmap)
    # mmap=False materializes but still caches per digest
    store2 = ArtifactStore(spool_dir=str(tmp_path / "spool2"), mmap=False)
    arr2 = np.asarray(store2.load(a).data)
    assert not isinstance(arr2, np.memmap)


def test_store_second_instance_reuses_spool(saved_model, tmp_path):
    """A sibling replica process (modelled by a second store over the same
    spool_dir) maps the published spool instead of re-parsing the .npz."""
    *_, a, _ = saved_model
    spool = str(tmp_path / "spool")
    first = ArtifactStore(spool_dir=spool)
    first.load(a)
    tracer = _ListTracer()
    second = ArtifactStore(spool_dir=spool, tracer=tracer)
    model = second.load(a)
    ev = tracer.events[0]
    assert ev["hit"] is False  # its own process cache was cold
    assert ev["spooled"] is False  # but the host spool already existed
    assert model.summary()["n_train"] == 180


def test_store_corrupt_spool_falls_back_to_npz(saved_model, tmp_path):
    *_, a, _ = saved_model
    spool = str(tmp_path / "spool")
    ArtifactStore(spool_dir=spool).load(a)
    # mangle one spooled member; a fresh store must not serve it
    member = os.path.join(spool, file_digest(a), "data.npy")
    with open(member, "wb") as f:
        f.write(b"not a npy file")
    tracer = _ListTracer()
    store = ArtifactStore(spool_dir=spool, tracer=tracer)
    model = store.load(a)  # falls back to ClusterModel.load, no raise
    np.testing.assert_allclose(
        np.asarray(model.data), np.asarray(saved_model[0])
    )
    assert tracer.events[0]["hit"] is False


def test_store_raises_on_missing_artifact(tmp_path):
    store = ArtifactStore(spool_dir=str(tmp_path / "spool"))
    with pytest.raises(OSError):
        store.load(str(tmp_path / "missing.npz"))


# -- FitScheduler --------------------------------------------------------------


class _FakeFitModel:
    """Stands in for ClusterModel: save() writes a real file."""

    generation = None

    def save(self, path, compress=True):
        with open(path, "wb") as f:
            f.write(b"model-bytes")
        return path


class _FakeFitResult:
    def to_cluster_model(self, points, params):
        return _FakeFitModel()


def _fake_fit(points, params, trace=None):
    if points is None:
        raise ValueError("no points")
    return _FakeFitResult()


@pytest.mark.parametrize(
    "kw", [{"workers": 0}, {"queue_bound": 0}, {"quota_rps": -1.0}]
)
def test_scheduler_rejects_bad_knobs(tmp_path, kw):
    with pytest.raises(ValueError):
        FitScheduler(str(tmp_path), fit_fn=_fake_fit, **kw)


def test_scheduler_publishes_through_callback(tmp_path):
    tracer = _ListTracer()
    published = []

    class _Entry:
        generation = 7

    sched = FitScheduler(
        str(tmp_path), fit_fn=_fake_fit, tracer=tracer,
        publish=lambda t, p, m: published.append((t, p)) or _Entry(),
    )
    try:
        job = sched.submit("acme", np.zeros((4, 3)), reason="drift")
        assert job.wait(30.0)
        assert job.state == "published"
        assert job.generation == 7
        assert job.path.endswith("acme_gen0001.npz")
        assert os.path.exists(job.path)
        assert published == [("acme", job.path)]
        states = [e["state"] for e in tracer.events
                  if e["stage"] == "fit_job" and e["job"] == job.job_id]
        assert states == ["queued", "running", "published"]
        run_ev = [e for e in tracer.events if e.get("state") == "running"][0]
        assert run_ev["queued_s"] >= 0.0
        # a second job for the same tenant gets the next generation
        job2 = sched.submit("acme", np.zeros((4, 3)))
        assert job2.wait(30.0)
        assert job2.path.endswith("acme_gen0002.npz")
        assert sched.stats()["published"] == 2
    finally:
        sched.close()


def test_scheduler_failed_fit_is_isolated(tmp_path):
    tracer = _ListTracer()
    results = []
    sched = FitScheduler(
        str(tmp_path), fit_fn=_fake_fit, tracer=tracer,
        on_result=lambda ok, err: results.append((ok, err)),
    )
    try:
        bad = sched.submit("acme", None)  # _fake_fit raises on None
        assert bad.wait(30.0)
        assert bad.state == "failed"
        assert "no points" in bad.error
        assert results == [(False, bad.error)]
        fail_ev = [e for e in tracer.events if e.get("state") == "failed"][0]
        assert fail_ev["error"] == bad.error
        # the worker survived: a good job still completes
        good = sched.submit("acme", np.zeros((2, 3)))
        assert good.wait(30.0) and good.state == "published"
        assert results[-1] == (True, None)
        assert sched.stats()["failed"] == 1
    finally:
        sched.close()


def test_scheduler_quota_sheds_429(tmp_path):
    clock = [100.0]
    sched = FitScheduler(
        str(tmp_path), fit_fn=_fake_fit, quota_rps=1.0,
        clock=lambda: clock[0],
    )
    try:
        sched.submit("acme", np.zeros((2, 3)))  # burst token spent
        with pytest.raises(ShedRequest) as exc:
            sched.submit("acme", np.zeros((2, 3)))
        assert exc.value.status == 429
        assert exc.value.reason == "fit_quota"
        assert exc.value.retry_after_s > 0.0
        sched.submit("globex", np.zeros((2, 3)))  # per-tenant: unaffected
        clock[0] += 1.0  # refill buys the next job
        sched.submit("acme", np.zeros((2, 3)))
        assert sched.stats()["shed"] == 1
    finally:
        sched.close()


def test_scheduler_full_queue_sheds_503(tmp_path):
    release = threading.Event()

    def _slow_fit(points, params, trace=None):
        release.wait(30.0)
        return _FakeFitResult()

    sched = FitScheduler(
        str(tmp_path), fit_fn=_slow_fit, workers=1, queue_bound=1,
    )
    try:
        first = sched.submit("t0", np.zeros((2, 3)))  # occupies the worker
        deadline = time.monotonic() + 5.0
        while first.state == "queued" and time.monotonic() < deadline:
            time.sleep(0.01)
        sched.submit("t1", np.zeros((2, 3)))  # fills the queue
        with pytest.raises(ShedRequest) as exc:
            sched.submit("t2", np.zeros((2, 3)))
        assert exc.value.status == 503
        assert exc.value.reason == "fit_queue_full"
    finally:
        release.set()
        sched.close()
    assert sched.join(0.0) or True  # close drained; no hang


def test_scheduler_close_rejects_submit(tmp_path):
    sched = FitScheduler(str(tmp_path), fit_fn=_fake_fit)
    sched.close()
    with pytest.raises(RuntimeError, match="closed"):
        sched.submit("t0", np.zeros((2, 3)))


# -- router scaling seams ------------------------------------------------------


def _router(**kw):
    kw.setdefault("replicas", 2)
    return FleetRouter("/nonexistent/model.npz", **kw)


def test_router_signals_reflect_inflight_and_latency_window():
    router = _router(replicas=2)
    r0, r1 = router.replicas
    router._mark(r0, True)
    sig = router.signals()
    assert sig["replicas"] == 2 and sig["up"] == 1
    assert sig["window"] == 0 and "p99_s" not in sig
    r0.in_flight = 3
    router._lat.extend([0.01] * 98 + [0.5, 0.5])
    sig = router.signals()
    assert sig["in_flight"] == 3 and sig["in_flight_per_up"] == 3.0
    assert sig["p99_s"] == 0.5 and sig["p50_s"] == 0.01


def test_router_free_rid_reuses_lowest_gap():
    router = _router(replicas=3)
    assert router._free_rid() == "3"
    # drop r1: the next scale-up reuses its rid (and thus its WAL dir)
    router.replicas = [r for r in router.replicas if r.rid != "1"]
    assert router._free_rid() == "1"


def test_router_scale_ops_require_running_loop():
    router = _router()
    assert router.scale_up() is None  # no asyncio loop yet
    assert router.scale_down() is False


def test_replica_environ_injects_compile_cache(tmp_path):
    cache = str(tmp_path / "xla-cache")
    router = _router(compile_cache=cache)
    env = router._replica_environ(router.replicas[0])
    assert env["JAX_COMPILATION_CACHE_DIR"] == cache
    # an explicit env wins over the knob
    router2 = _router(
        compile_cache=cache,
        replica_env={"JAX_COMPILATION_CACHE_DIR": "/elsewhere"},
    )
    env2 = router2._replica_environ(router2.replicas[0])
    assert env2["JAX_COMPILATION_CACHE_DIR"] == "/elsewhere"
    router3 = _router(compile_cache="off")
    assert "JAX_COMPILATION_CACHE_DIR" not in router3._replica_environ(
        router3.replicas[0]
    )


def test_rebuild_ring_tracks_membership():
    router = _router(replicas=3, policy="consistent_hash")
    import json

    body = json.dumps({"tenant": "acme", "points": []}).encode()
    before = {router._route_order("/predict", body)[0].rid}
    router.replicas = router.replicas[:2]
    router._rebuild_ring()
    order = router._route_order("/predict", body)
    assert len(order) == 2
    assert all(r.rid in ("0", "1") for r in order)
    assert before  # ring was usable before and after
