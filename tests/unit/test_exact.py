"""Blocked exact (Random Blocks) path vs the dense single-block path.

The tiled Borůvka (``models/exact.py`` + ``ops/tiled.py``) must reproduce the
dense in-memory result: same MST weight multiset, same condensed tree, same
flat labels — with tiles much smaller than the dataset so every code path
(row loop, column loop, padding, cross-tile argmin) is exercised.
"""

import numpy as np
import pytest

from hdbscan_tpu.config import HDBSCANParams
from hdbscan_tpu.models import exact, hdbscan
from hdbscan_tpu.ops.tiled import knn_core_distances
from tests.conftest import make_blobs


def _params(**kw):
    base = dict(min_points=4, min_cluster_size=5)
    base.update(kw)
    return HDBSCANParams(**base)


def test_knn_core_distances_match_dense(rng):
    data, _ = make_blobs(rng, n=90, d=3)
    from hdbscan_tpu.core.knn import core_distances

    import jax.numpy as jnp

    for min_pts in (1, 2, 4, 9):
        core, knn = knn_core_distances(
            data, min_pts, row_tile=16, col_tile=128, dtype=np.float64
        )
        dense = np.asarray(core_distances(jnp.asarray(data), min_pts))
        np.testing.assert_allclose(core, dense, rtol=1e-9, atol=1e-12)
        assert np.all(np.diff(knn, axis=1) >= -1e-12)  # ascending lists


def test_exact_matches_dense_block(rng):
    data, _ = make_blobs(rng, n=140, d=3, centers=3)
    p = _params()
    dense = hdbscan.fit(data, p)
    blocked = exact.fit(data, p, row_tile=16, col_tile=128, dtype=np.float64)
    # Same MST weight multiset (edge identities may differ under ties).
    np.testing.assert_allclose(
        np.sort(blocked.mst[2]), np.sort(dense.mst[2]), rtol=1e-9
    )
    np.testing.assert_allclose(blocked.core_distances, dense.core_distances, rtol=1e-9)
    assert blocked.mst[0].shape == (len(data) - 1,)
    # Identical clustering.
    np.testing.assert_array_equal(blocked.labels, dense.labels)
    np.testing.assert_allclose(
        np.sort(blocked.tree.stability), np.sort(dense.tree.stability), rtol=1e-6
    )


@pytest.mark.parametrize("metric", ["manhattan", "cosine"])
def test_exact_other_metrics(rng, metric):
    data, _ = make_blobs(rng, n=80, d=4, centers=2)
    data = np.abs(data) + 0.1  # keep cosine well-defined
    p = _params(dist_function=metric)
    dense = hdbscan.fit(data, p)
    blocked = exact.fit(data, p, row_tile=16, col_tile=128, dtype=np.float64)
    np.testing.assert_allclose(
        np.sort(blocked.mst[2]), np.sort(dense.mst[2]), rtol=1e-9
    )
    np.testing.assert_array_equal(blocked.labels, dense.labels)


def test_exact_iris_golden(iris):
    """The bundled 149x4 dataset with the reference's hard-coded params
    (minPts=4, minClSize=4, ``main/Main.java:71``)."""
    p = HDBSCANParams(min_points=4, min_cluster_size=4)
    dense = hdbscan.fit(iris, p)
    blocked = exact.fit(iris, p, row_tile=32, col_tile=128, dtype=np.float64)
    np.testing.assert_array_equal(blocked.labels, dense.labels)
    np.testing.assert_allclose(
        np.sort(blocked.mst[2]), np.sort(dense.mst[2]), rtol=1e-9
    )


def test_exact_single_cluster(rng):
    data = rng.normal(size=(60, 2)) * 0.01
    p = _params(min_cluster_size=10)
    blocked = exact.fit(data, p, row_tile=8, col_tile=128, dtype=np.float64)
    dense = hdbscan.fit(data, p)
    np.testing.assert_array_equal(blocked.labels, dense.labels)
