"""Native merge-forest must match the pure-Python implementation exactly."""

import os

import numpy as np
import pytest

from hdbscan_tpu.core import tree as tree_mod
from hdbscan_tpu.native import merge_forest_lib
from tests.conftest import make_blobs


def _python_forest(n, u, v, w, pw=None):
    """Force the pure-Python path regardless of compiler availability."""
    import hdbscan_tpu.native as native

    saved = native._lib, native._lib_tried
    native._lib, native._lib_tried = None, True
    try:
        return tree_mod.build_merge_forest(n, u, v, w, point_weights=pw)
    finally:
        native._lib, native._lib_tried = saved


@pytest.mark.skipif(merge_forest_lib() is None, reason="no C compiler")
class TestNativeMergeForest:
    def _compare(self, n, u, v, w, pw=None):
        a = _python_forest(n, u, v, w, pw)
        b = tree_mod.build_merge_forest(n, u, v, w, point_weights=pw)
        assert a.n_points == b.n_points
        assert a.roots == b.roots
        np.testing.assert_allclose(b.dist, a.dist)
        np.testing.assert_allclose(b.sizes, a.sizes)
        assert len(a.children) == len(b.children)
        for ca, cb in zip(a.children, b.children):
            if ca is None:
                assert cb is None
            else:
                assert sorted(ca) == sorted(cb)

    def test_random_edges(self, rng):
        n = 200
        u = rng.integers(0, n, 600)
        v = rng.integers(0, n, 600)
        w = rng.uniform(0, 5, 600)
        keep = u != v
        self._compare(n, u[keep], v[keep], w[keep])

    def test_tie_heavy_lattice(self, rng):
        """Integer-grid distances: massive tie groups exercise contraction."""
        pts = rng.integers(0, 5, size=(150, 2)).astype(float)
        d = np.abs(pts[:, None, :] - pts[None, :, :]).sum(-1)
        iu, iv = np.triu_indices(150, 1)
        sel = rng.choice(len(iu), 2000, replace=False)
        self._compare(150, iu[sel], iv[sel], d[iu[sel], iv[sel]])

    def test_weighted_points(self, rng):
        pts, _ = make_blobs(rng, n=120, d=2, centers=3)
        d = np.sqrt(((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1))
        iu, iv = np.triu_indices(120, 1)
        pw = rng.integers(1, 9, 120).astype(float)
        self._compare(120, iu, iv, d[iu, iv], pw=pw)

    def test_full_clustering_identical(self, rng):
        """End-to-end labels must be identical through either implementation."""
        from hdbscan_tpu.config import HDBSCANParams
        from hdbscan_tpu.models import hdbscan

        pts, _ = make_blobs(rng, n=400, d=3, centers=3)
        import hdbscan_tpu.native as native

        res_native = hdbscan.fit(pts, HDBSCANParams(min_points=5, min_cluster_size=10))
        saved = native._lib, native._lib_tried
        native._lib, native._lib_tried = None, True
        try:
            res_py = hdbscan.fit(pts, HDBSCANParams(min_points=5, min_cluster_size=10))
        finally:
            native._lib, native._lib_tried = saved
        np.testing.assert_array_equal(res_native.labels, res_py.labels)
