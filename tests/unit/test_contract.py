"""Property tests for the vectorized Borůvka contraction round
(utils/unionfind.contract_min_edges) — the host-side replacement for the
per-edge union loops, exercised under adversarial weight ties (longer-than-2
functional-graph cycles) and validated against brute-force MST weight."""

import numpy as np
import pytest

from hdbscan_tpu.utils.unionfind import contract_min_edges


def _brute_mst_weight(dist):
    # Prim over the dense matrix
    n = len(dist)
    in_tree = np.zeros(n, bool)
    in_tree[0] = True
    best = dist[0].copy()
    total = 0.0
    for _ in range(n - 1):
        best_masked = np.where(in_tree, np.inf, best)
        j = int(np.argmin(best_masked))
        total += best_masked[j]
        in_tree[j] = True
        best = np.minimum(best, dist[j])
    return total


def _boruvka_via_contract(dist):
    n = len(dist)
    comp = np.arange(n, dtype=np.int64)
    total, edges = 0.0, 0
    for _ in range(64):
        uc = np.unique(comp)
        if len(uc) <= 1:
            break
        # per-vertex min outgoing candidate (smallest column tie-break,
        # matching the device scan's semantics)
        out = comp[None, :] != comp[:, None]
        w = np.where(out, dist, np.inf)
        bj = np.argmin(w, axis=1).astype(np.int64)
        bw = w[np.arange(n), bj]
        bj = np.where(np.isfinite(bw), bj, -1)
        emit, comp, _ = contract_min_edges(comp, bj, bw)
        total += float(bw[emit].sum())
        edges += len(emit)
    return total, edges, comp


@pytest.mark.parametrize("seed", range(5))
def test_contract_builds_exact_mst_random(seed):
    rng = np.random.default_rng(seed)
    n = 60
    pts = rng.normal(size=(n, 3))
    dist = np.linalg.norm(pts[:, None] - pts[None, :], axis=2)
    np.fill_diagonal(dist, np.inf)
    total, edges, comp = _boruvka_via_contract(dist)
    assert edges == n - 1
    assert len(np.unique(comp)) == 1
    assert total == pytest.approx(_brute_mst_weight(dist), rel=1e-9)


@pytest.mark.parametrize("seed", range(5))
def test_contract_with_massive_ties(seed):
    # Lattice-valued distances: huge numbers of exactly tied weights, the
    # regime where functional-graph cycles can exceed length 2.
    rng = np.random.default_rng(100 + seed)
    n = 80
    pts = rng.integers(0, 4, size=(n, 2)).astype(float)
    dist = np.abs(pts[:, None] - pts[None, :]).sum(axis=2)
    np.fill_diagonal(dist, np.inf)
    # coincident points: distance 0 ties everywhere
    total, edges, comp = _boruvka_via_contract(dist)
    assert edges == n - 1
    assert len(np.unique(comp)) == 1
    assert total == pytest.approx(_brute_mst_weight(dist), rel=1e-9)


def test_contract_handles_isolated_components():
    # two groups with no candidates at all: nothing emitted, labels unchanged
    comp = np.array([0, 0, 1, 1])
    cand_j = np.full(4, -1)
    cand_w = np.zeros(4)
    emit, comp2, n_comp = contract_min_edges(comp, cand_j, cand_w)
    assert len(emit) == 0
    assert n_comp == 2
    np.testing.assert_array_equal(comp, comp2)


def test_contract_two_components_one_edge():
    comp = np.array([7, 7, 9, 9])
    # vertices 1 and 2 pick each other (the same physical edge, both sides)
    cand_j = np.array([-1, 2, 1, -1])
    cand_w = np.array([np.inf, 0.5, 0.5, np.inf])
    emit, comp2, n_comp = contract_min_edges(comp, cand_j, cand_w)
    assert n_comp == 1
    assert len(emit) == 1  # the shared edge enters once
    assert len(np.unique(comp2)) == 1


def test_ari_sparse_contingency_scales_to_noise_heavy_labelings():
    # Regression: with noise-as-singletons BOTH sides can carry ~n distinct
    # labels; the dense contingency matrix was O(n^2) memory (hung real
    # benchmark runs at 200k). Sparse pair counting must handle it instantly
    # and agree with the dense result on small inputs.
    import time

    from hdbscan_tpu.utils.evaluation import adjusted_rand_index

    rng = np.random.default_rng(0)
    n = 200_000
    a = np.zeros(n, np.int64)  # all noise -> n singletons
    b = np.zeros(n, np.int64)
    a[: n // 2] = 1
    b[: n // 3] = 1
    t0 = time.monotonic()
    v = adjusted_rand_index(a, b)
    assert time.monotonic() - t0 < 10.0
    assert -0.5 <= v <= 1.0
    # exact agreement of the sparse path with a hand-built dense contingency
    a2 = rng.integers(0, 5, 300)
    b2 = rng.integers(0, 4, 300)
    ai = a2.copy(); bi = b2.copy()
    cont = np.zeros((5, 4))
    for x, y_ in zip(ai, bi):
        cont[x, y_] += 1
    comb2 = lambda x: x * (x - 1) / 2.0
    sum_ij = comb2(cont).sum(); sum_a = comb2(cont.sum(1)).sum(); sum_b = comb2(cont.sum(0)).sum()
    total = comb2(300)
    expected = sum_a * sum_b / total
    want = (sum_ij - expected) / ((sum_a + sum_b) / 2.0 - expected)
    got = adjusted_rand_index(a2, b2, noise_as_singletons=False)
    np.testing.assert_allclose(got, want, rtol=1e-12)
