"""L5 batched-block and nearest-sample kernel tests (8-device CPU mesh)."""

import jax
import numpy as np
import pytest

from hdbscan_tpu.models.hdbscan import hdbscan_block_edges
from hdbscan_tpu.parallel.blocks import (
    nearest_sample_assign,
    pack_blocks,
    run_packed_blocks,
)
from hdbscan_tpu.parallel.mesh import get_mesh
from tests.conftest import make_blobs


class TestNearestSample:
    def test_matches_bruteforce(self, rng):
        pts = rng.normal(size=(500, 4))
        samples = pts[rng.choice(500, 37, replace=False)]
        got = nearest_sample_assign(pts, samples, tile=128)
        d2 = ((pts[:, None, :] - samples[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(got, d2.argmin(1))

    def test_samples_map_to_themselves(self, rng):
        pts = rng.normal(size=(100, 3))
        idx = rng.choice(100, 10, replace=False)
        got = nearest_sample_assign(pts, pts[idx])
        np.testing.assert_array_equal(got[idx], np.arange(10))


class TestPackedBlocks:
    def test_pack_shapes(self, rng):
        data = rng.normal(size=(60, 3))
        subsets = [np.arange(0, 20), np.arange(20, 45), np.arange(45, 60)]
        packed = pack_blocks(data, subsets, capacity=30)
        assert packed.x.shape == (3, 30, 3)
        np.testing.assert_array_equal(packed.num_valid, [20, 25, 15])
        assert packed.point_index[0, 19] == 19 and packed.point_index[0, 20] == -1

    def test_overflow_raises(self, rng):
        data = rng.normal(size=(10, 2))
        with pytest.raises(ValueError):
            pack_blocks(data, [np.arange(10)], capacity=5)

    def test_batched_mst_matches_single(self, rng):
        """Each block's MST from the batched kernel == the single-block path."""
        data, _ = make_blobs(rng, n=90, d=3, centers=3)
        subsets = [np.arange(0, 30), np.arange(30, 60), np.arange(60, 90)]
        packed = pack_blocks(data, subsets, capacity=40)
        u, v, w, core = run_packed_blocks(packed, min_pts=4)
        for ids in subsets:
            su, sv, sw, score = hdbscan_block_edges(data[ids], min_pts=4)
            sel = np.isin(u, ids) & np.isin(v, ids)
            got_w = np.sort(w[sel])
            np.testing.assert_allclose(got_w, np.sort(sw), rtol=1e-9)
        # core distances scattered back per block
        for i, ids in enumerate(subsets):
            _, _, _, score = hdbscan_block_edges(data[ids], min_pts=4)
            np.testing.assert_allclose(core[i, : len(ids)], score, rtol=1e-9)

    def test_batch_padding_adds_no_edges(self, rng):
        data = rng.normal(size=(20, 2))
        packed = pack_blocks(data, [np.arange(20)], capacity=25)
        u1, v1, w1, _ = run_packed_blocks(packed, min_pts=3, batch_pad=1)
        u8, v8, w8, _ = run_packed_blocks(packed, min_pts=3, batch_pad=8)
        np.testing.assert_allclose(np.sort(w1), np.sort(w8), rtol=1e-12)

    def test_runs_on_mesh(self, rng):
        """Blocks shard over the 8-device CPU mesh and produce identical edges."""
        assert len(jax.devices()) == 8
        data, _ = make_blobs(rng, n=160, d=3, centers=4)
        subsets = [np.arange(i * 20, (i + 1) * 20) for i in range(8)]
        packed = pack_blocks(data, subsets, capacity=20)
        mesh = get_mesh()
        u_m, v_m, w_m, core_m = run_packed_blocks(packed, min_pts=4, mesh=mesh, batch_pad=8)
        u_s, v_s, w_s, core_s = run_packed_blocks(packed, min_pts=4)
        np.testing.assert_allclose(np.sort(w_m), np.sort(w_s), rtol=1e-12)
        np.testing.assert_allclose(core_m, core_s, rtol=1e-12)
