"""Observability layer (utils/tracing sinks + utils/telemetry): JSONL sink
line contract, counter attribution, report aggregates, manifest/memory
sampling, and the multi-host trace merge."""

import json

import numpy as np

from hdbscan_tpu.utils import telemetry
from hdbscan_tpu.utils.tracing import TRACE_SCHEMA, JsonlSink, Tracer


class TestJsonlSink:
    def test_line_contract(self, tmp_path):
        """Every line: schema tag, increasing seq, stage, float wall_s,
        static fields, sanitized event fields."""
        path = str(tmp_path / "trace.jsonl")
        t = Tracer(sinks=[JsonlSink(path, static={"process": 3})])
        t("alpha", wall_s=0.5, rows=np.int64(7), frac=np.float32(0.25))
        t("beta", flag=np.bool_(True), arr=np.arange(2))
        t.close()
        lines = [json.loads(s) for s in open(path) if s.strip()]
        assert [ev["seq"] for ev in lines] == [0, 1]
        for ev in lines:
            assert ev["schema"] == TRACE_SCHEMA
            assert ev["process"] == 3
            assert isinstance(ev["stage"], str)
            assert isinstance(ev["wall_s"], float)
        assert lines[0]["rows"] == 7 and lines[0]["frac"] == 0.25
        assert lines[1]["flag"] is True and lines[1]["arr"] == [0, 1]

    def test_close_idempotent_and_flush_per_line(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        sink = JsonlSink(path)
        t = Tracer(sinks=[sink])
        t("work", wall_s=1.0)
        # Flushed before close: a killed run keeps its partial trace.
        assert json.loads(open(path).readline())["stage"] == "work"
        t.close()
        t.close()

    def test_stream_sugar_unchanged(self, tmp_path):
        """Tracer(stream=...) still prints logfmt lines (pre-sink API)."""
        out = open(tmp_path / "log.txt", "w+")
        t = Tracer(stream=out)
        t("work", n=1, wall_s=0.25)
        out.seek(0)
        assert "stage=work" in out.read()
        out.close()


class TestTracerCounters:
    def test_delta_attribution(self):
        """Counter deltas since the previous emit ride the NEXT event; zero
        deltas are omitted (phase events emit at phase END, so the compiles
        a phase triggered land on its own event)."""
        box = [0]
        t = Tracer(counters={"jit_compiles": lambda: box[0]})
        box[0] = 2
        t("phase_a", wall_s=0.1)
        t("phase_b", wall_s=0.2)  # no compiles since phase_a
        box[0] = 5
        t("phase_c", wall_s=0.3)
        assert t.events[0].fields["jit_compiles"] == 2
        assert "jit_compiles" not in t.events[1].fields
        assert t.events[2].fields["jit_compiles"] == 3

    def test_summary_sorted_by_wall_desc(self):
        t = Tracer()
        t("cheap", wall_s=0.1)
        t("dear", wall_s=2.0)
        t("cheap", wall_s=0.2)
        lines = t.summary().splitlines()
        assert lines[0].startswith("dear:")
        assert lines[1] == "cheap: n=2 wall_s=0.300"


class TestSanitize:
    def test_numpy_and_nested(self):
        out = telemetry.json_sanitize(
            {
                "i": np.int32(3),
                "f": np.float64(0.5),
                "b": np.bool_(False),
                "a": np.array([[1, 2]]),
                "t": (1, np.int8(2)),
                "n": None,
                "o": object(),
            }
        )
        assert out["i"] == 3 and out["f"] == 0.5 and out["b"] is False
        assert out["a"] == [[1, 2]] and out["t"] == [1, 2] and out["n"] is None
        assert isinstance(out["o"], str)
        json.dumps(out)  # round-trips


class TestPhaseAggregates:
    def test_sums_and_rates(self):
        t = Tracer()
        t("scan", wall_s=1.0, gflops=10.0, gbytes=1.0, pad_gflops=2.0)
        t("scan", wall_s=1.0, gflops=30.0, gbytes=3.0, jit_compiles=4)
        t("tree", wall_s=0.5)
        agg = telemetry.phase_aggregates(t.events)
        assert list(agg) == ["scan", "tree"]  # wall-descending
        scan = agg["scan"]
        assert scan["count"] == 2 and scan["wall_s"] == 2.0
        assert scan["gflops"] == 40.0 and scan["pad_gflops"] == 2.0
        assert scan["gflops_s"] == 20.0  # summed gflops over summed wall
        assert scan["jit_compiles"] == 4
        assert agg["tree"] == {"count": 1, "wall_s": 0.5}

    def test_accepts_jsonl_dicts(self):
        events = [
            {"stage": "scan", "wall_s": 1.5, "gflops": 3.0},
            {"stage": "scan", "wall_s": 0.5},
        ]
        agg = telemetry.phase_aggregates(events)
        assert agg["scan"]["count"] == 2
        assert agg["scan"]["wall_s"] == 2.0
        assert agg["scan"]["gflops"] == 3.0

    def test_report_walls_match_tracer_total(self):
        t = Tracer()
        for w in (0.125, 0.25, 0.0625):
            t("scan", wall_s=w)
        report = telemetry.build_report(t)
        assert report["schema"] == telemetry.REPORT_SCHEMA
        assert abs(report["phases"]["scan"]["wall_s"] - t.total("scan")) < 1e-6
        assert report["event_count"] == 3


class TestManifestAndMemory:
    def test_manifest_fields(self, monkeypatch):
        from hdbscan_tpu.config import HDBSCANParams

        monkeypatch.setenv("HDBSCAN_TPU_PEAK_FLOPS", "1e12")
        m = telemetry.run_manifest(
            HDBSCANParams(min_points=5), argv=["file=x"], extra={"tag": "t"}
        )
        assert m["params"]["min_points"] == 5
        assert m["argv"] == ["file=x"]
        assert m["topology"]["device_count"] >= 1
        assert m["backends"]["default_backend"] == "cpu"
        assert m["env"]["HDBSCAN_TPU_PEAK_FLOPS"] == "1e12"
        assert m["tag"] == "t"
        json.dumps(m)

    def test_memory_sample_shape(self):
        s = telemetry.sample_device_memory()
        # CPU backend has no allocator stats -> live-array fallback.
        assert s["source"] in ("memory_stats", "live_arrays")
        if s["source"] == "live_arrays":
            assert s["live_array_count"] >= 0
            assert s["live_array_bytes"] >= 0
        json.dumps(s)

    def test_compile_counter_counts_new_compiles(self):
        import jax
        import jax.numpy as jnp

        fn = telemetry.compile_counter()
        before = fn()

        @jax.jit
        def f(x):
            return x * 2 + 1

        f(jnp.arange(7))  # fresh jaxpr + shape -> one backend compile
        assert fn() > before


class TestHostMerge:
    def test_trace_path_for_process(self):
        assert telemetry.trace_path_for_process("t.jsonl", 0, 1) == "t.jsonl"
        assert telemetry.trace_path_for_process("t.jsonl", 2, 4) == "t.2.jsonl"
        assert telemetry.host_trace_paths("t.jsonl", 2) == [
            "t.0.jsonl",
            "t.1.jsonl",
        ]

    def test_merge_two_hosts_and_missing(self, tmp_path):
        base = str(tmp_path / "trace.jsonl")
        for pid, wall in ((0, 1.0), (1, 3.0)):
            t = Tracer(
                sinks=[
                    JsonlSink(
                        telemetry.trace_path_for_process(base, pid, 3),
                        static={"process": pid},
                    )
                ]
            )
            t("scan", wall_s=wall)
            t("scan", wall_s=wall)
            t.close()
        merged = telemetry.merge_host_traces(telemetry.host_trace_paths(base, 3))
        assert merged["0"]["scan"]["wall_s"] == 2.0
        assert merged["1"]["scan"]["wall_s"] == 6.0
        assert merged["1"]["scan"]["count"] == 2
        assert merged["2"] == {"missing": True}  # dead rank is a finding

    def test_read_trace_roundtrip(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        t = Tracer(sinks=[JsonlSink(path)])
        t("a", wall_s=0.5, rows=3)
        t.close()
        events = telemetry.read_trace(path)
        assert events[0]["stage"] == "a" and events[0]["rows"] == 3


class TestCheckTraceScript:
    def test_valid_artifacts_pass(self, tmp_path):
        from scripts import check_trace

        trace = str(tmp_path / "t.jsonl")
        report = str(tmp_path / "r.json")
        t = Tracer(sinks=[JsonlSink(trace)])
        t("scan", wall_s=0.5, gflops=1.0)
        t("scan", wall_s=0.25)
        t("tree", wall_s=0.125)
        t.close()
        telemetry.write_report(report, telemetry.build_report(t))
        events, errors = check_trace.validate_trace(trace)
        assert len(events) == 3 and errors == []
        _, errors = check_trace.validate_report(report, trace_events=events)
        assert errors == []
        assert check_trace.main([trace, report]) == 0

    def test_violations_detected(self, tmp_path):
        from scripts import check_trace

        bad = tmp_path / "bad.jsonl"
        bad.write_text(
            json.dumps({"schema": "wrong/1", "stage": "s", "wall_s": 0.1})
            + "\nnot json\n"
            + json.dumps({"schema": TRACE_SCHEMA, "stage": 5, "wall_s": "x"})
            + "\n"
        )
        _, errors = check_trace.validate_trace(str(bad))
        assert len(errors) >= 3
        assert check_trace.main([str(bad)]) == 1

    def test_ring_invariants(self, tmp_path):
        """Ring events (parallel/ring.py): one full panel rotation is
        exactly devices - 1 permutes, and each device's wall events keep a
        monotonic seq — a dropped permute or reordered timeline must fail
        the validator."""
        from scripts import check_trace

        good = tmp_path / "ring_ok.jsonl"
        lines = [
            {"schema": TRACE_SCHEMA, "stage": "ring_knn_scan", "wall_s": 0.5,
             "devices": 8, "ppermute_steps": 7, "seq": 1, "process": 0},
            {"schema": TRACE_SCHEMA, "stage": "ring_device_wall",
             "wall_s": 0.1, "device": 0, "seq": 2, "process": 0},
            {"schema": TRACE_SCHEMA, "stage": "ring_device_wall",
             "wall_s": 0.2, "device": 1, "seq": 3, "process": 0},
            {"schema": TRACE_SCHEMA, "stage": "ring_device_wall",
             "wall_s": 0.3, "device": 0, "seq": 4, "process": 0},
        ]
        good.write_text("".join(json.dumps(e) + "\n" for e in lines))
        _, errors = check_trace.validate_trace(str(good))
        assert errors == []

        bad = tmp_path / "ring_bad.jsonl"
        lines = [
            # A dropped permute: 8 devices but only 6 steps.
            {"schema": TRACE_SCHEMA, "stage": "ring_knn_scan", "wall_s": 0.5,
             "devices": 8, "ppermute_steps": 6, "seq": 1, "process": 0},
            # Device 0's timeline goes backwards.
            {"schema": TRACE_SCHEMA, "stage": "ring_device_wall",
             "wall_s": 0.1, "device": 0, "seq": 5, "process": 0},
            {"schema": TRACE_SCHEMA, "stage": "ring_device_wall",
             "wall_s": 0.1, "device": 0, "seq": 4, "process": 0},
        ]
        bad.write_text("".join(json.dumps(e) + "\n" for e in lines))
        _, errors = check_trace.validate_trace(str(bad))
        assert any("ppermute_steps" in e for e in errors)
        assert any("device 0 seq" in e for e in errors)
        assert check_trace.main([str(bad)]) == 1

    def test_sharded_mst_invariants(self, tmp_path):
        """In-jit sharded Borůvka schema (parallel/shard.py): ``mst_round``
        events tagged ``sharded: true`` must be contiguous with strictly
        decreasing ``components``, and every ``shard_mst_device`` fit must
        land exactly one ``host_sync``."""
        import json

        from scripts import check_trace

        def fit_events(seq0, comps=(57, 9, 1)):
            evs = [
                {"schema": TRACE_SCHEMA, "stage": "shard_mst_device",
                 "wall_s": 0.4, "devices": 8, "rounds": len(comps),
                 "n": 600, "shard": 128, "seq": seq0, "process": 0},
            ]
            for r, c in enumerate(comps):
                evs.append(
                    {"schema": TRACE_SCHEMA, "stage": "mst_round",
                     "wall_s": 0.0, "round": r, "components": c,
                     "edges_added": 1, "sharded": True,
                     "seq": seq0 + 1 + r, "process": 0}
                )
            evs.append(
                {"schema": TRACE_SCHEMA, "stage": "host_sync", "wall_s": 0.1,
                 "arrays": 10, "bytes": 4096,
                 "seq": seq0 + 1 + len(comps), "process": 0}
            )
            evs.append(
                {"schema": TRACE_SCHEMA, "stage": "tree_build_device",
                 "wall_s": 0.1, "fallback": False, "nodes": 599,
                 "backend": "device",
                 "seq": seq0 + 2 + len(comps), "process": 0}
            )
            return evs

        good = tmp_path / "shard_mst_ok.jsonl"
        good.write_text(
            "".join(json.dumps(e) + "\n" for e in fit_events(1))
        )
        _, errors = check_trace.validate_trace(str(good))
        assert errors == []

        # A round that fails to contract — the while_loop looped for free.
        stall = tmp_path / "shard_mst_stall.jsonl"
        stall.write_text(
            "".join(
                json.dumps(e) + "\n" for e in fit_events(1, comps=(57, 57, 9))
            )
        )
        _, errors = check_trace.validate_trace(str(stall))
        assert any("did not decrease" in e for e in errors)

        # A dropped round: the replayed counter must be contiguous.
        gap = tmp_path / "shard_mst_gap.jsonl"
        evs = fit_events(1)
        evs[2]["round"] = 2  # 0, 2, 2...
        gap.write_text("".join(json.dumps(e) + "\n" for e in evs))
        _, errors = check_trace.validate_trace(str(gap))
        assert any("not contiguous" in e for e in errors)

        # Two sharded device fits but only one host_sync between them.
        twofit = tmp_path / "shard_mst_twofit.jsonl"
        evs = fit_events(1)
        evs.insert(1, {"schema": TRACE_SCHEMA, "stage": "shard_mst_device",
                       "wall_s": 0.4, "devices": 8, "rounds": 3, "n": 600,
                       "shard": 128, "seq": 99, "process": 0})
        twofit.write_text("".join(json.dumps(e) + "\n" for e in evs))
        _, errors = check_trace.validate_trace(str(twofit))
        assert any("sync exactly once" in e for e in errors)
        assert check_trace.main([str(twofit)]) == 1

    def test_wall_mismatch_detected(self, tmp_path):
        from scripts import check_trace

        trace = str(tmp_path / "t.jsonl")
        report = str(tmp_path / "r.json")
        t = Tracer(sinks=[JsonlSink(trace)])
        t("scan", wall_s=0.5)
        t.close()
        rep = telemetry.build_report(t)
        rep["phases"]["scan"]["wall_s"] += 1e-3  # outside tolerance
        telemetry.write_report(report, rep)
        events, _ = check_trace.validate_trace(trace)
        _, errors = check_trace.validate_report(report, trace_events=events)
        assert any("wall_s" in e for e in errors)


def test_check_trace_is_stdlib_only():
    """The validator must run where artifacts land, without jax/numpy."""
    import ast

    from scripts import check_trace

    src = open(check_trace.__file__).read()
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mods = [node.module or ""]
        else:
            continue
        for mod in mods:
            assert mod.split(".")[0] in (
                "__future__", "json", "math", "os", "sys",
            ), mod
