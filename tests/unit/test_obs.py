"""Deep-observability layer (hdbscan_tpu/obs): memory auditor, replication
gate, heartbeats + watchdog, and fleet span correlation.

Covers the ISSUE acceptance legs that fit in the unit lane:

- the ``live_arrays`` sampling fallback attributes a known buffer's bytes
  to its device on CPU (where ``memory_stats`` is unavailable);
- ``assert_not_replicated`` trips on a deliberately replicated buffer on
  the 8-device virtual mesh (conftest forces
  ``--xla_force_host_platform_device_count=8``) and passes the properly
  sharded equivalent of the same logical array;
- a stalled phase — injected through the existing fault harness's
  ``phase_stall`` site — makes the watchdog dump thread stacks within
  ``watchdog_s``, while a healthy beating loop produces zero stalls;
- heartbeat progress is monotone and the emitted events satisfy
  ``scripts/check_trace.py``'s obs schemas.
"""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hdbscan_tpu import obs
from hdbscan_tpu.fault import inject
from hdbscan_tpu.obs.audit import (
    MemoryAuditor,
    ReplicatedBufferError,
    sample_per_device,
)
from hdbscan_tpu.obs.heartbeat import Heartbeats
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer


@pytest.fixture(autouse=True)
def _clean_installs():
    """Never leak a process-global auditor/hub/fault-plan across tests."""
    yield
    obs.clear()
    inject.clear()


def _events(tracer, stage):
    return [e for e in tracer.events if e.name == stage]


# -- sampling ---------------------------------------------------------------


def test_live_arrays_attributes_buffer_to_device():
    keep = jnp.arange(4096, dtype=jnp.float64)  # 32 KiB pinned live
    keep.block_until_ready()
    per_dev, src = sample_per_device(source="live_arrays")
    assert src == "live_arrays"
    # A single-device array lands whole on cpu:0 of the virtual mesh.
    assert per_dev.get("cpu:0", 0) >= keep.nbytes
    assert set(per_dev) == {f"cpu:{d.id}" for d in jax.devices()}
    del keep


def test_memory_stats_forced_raises_on_cpu():
    with pytest.raises(RuntimeError, match="memory_stats unavailable"):
        sample_per_device(source="memory_stats")


def test_sample_source_validated():
    with pytest.raises(ValueError, match="source must be"):
        sample_per_device(source="psutil")


def test_auditor_phase_watermarks_and_events():
    tracer = Tracer()
    aud = MemoryAuditor(tracer=tracer, interval_s=0.005, source="live_arrays")
    with aud.phase("alloc"):
        keep = jnp.ones((2048, 8), dtype=jnp.float64)  # 128 KiB
        keep.block_until_ready()
    table = aud.watermark_table()
    assert set(table) == {"alloc"}
    wm = table["alloc"]
    assert wm["source"] == "live_arrays"
    assert wm["samples"] >= 2  # entry + exit at minimum
    assert wm["max_device_bytes"] >= keep.nbytes
    assert wm["per_device"]["cpu:0"] >= keep.nbytes
    # peak event dominates every sample, as check_trace enforces.
    samples = _events(tracer, "mem_sample")
    peaks = _events(tracer, "mem_phase_peak")
    assert len(peaks) == 1 and len(samples) == wm["samples"]
    peak = peaks[0].fields
    assert peak["max_device_bytes"] == wm["max_device_bytes"]
    assert all(
        s.fields["max_device_bytes"] <= peak["max_device_bytes"]
        for s in samples
    )
    assert aud.device_peaks()["cpu:0"] >= keep.nbytes
    del keep


def test_auditor_merges_repeated_phases():
    aud = MemoryAuditor(interval_s=0.005, source="live_arrays")
    with aud.phase("p"):
        pass
    first = aud.watermark_table()["p"]["samples"]
    with aud.phase("p"):
        pass
    assert aud.watermark_table()["p"]["samples"] >= first + 2


def test_auditor_knob_validation():
    with pytest.raises(ValueError, match="interval_s"):
        MemoryAuditor(interval_s=0.0)
    with pytest.raises(ValueError, match="source"):
        MemoryAuditor(source="top")


# -- the replication gate ---------------------------------------------------


def _mesh():
    return Mesh(np.array(jax.devices()), ("i",))


def test_gate_trips_on_replicated_buffer():
    """A buffer replicated whole onto all 8 virtual devices trips the gate
    that the sharded version of the same array passes (ISSUE acceptance).

    The host array goes in via numpy so no full-size jax intermediate ever
    touches a device — the only device bytes are the ones under test."""
    aud = MemoryAuditor(interval_s=0.005, source="live_arrays")
    n, itemsize = 4096, 8  # threshold = 0.5 * 32768 = 16384 B/device
    host = np.arange(n, dtype=np.float64)
    with aud.phase("replicated"):
        bad = jax.device_put(
            host, NamedSharding(_mesh(), P())  # every device holds all n
        )
        bad.block_until_ready()  # live at the phase-exit sample
    with pytest.raises(ReplicatedBufferError, match="replicated"):
        aud.assert_not_replicated(n, itemsize)
    del bad


def test_gate_passes_sharded_buffer():
    aud = MemoryAuditor(interval_s=0.005, source="live_arrays")
    n, itemsize = 4096, 8
    host = np.arange(n, dtype=np.float64)
    with aud.phase("sharded"):
        ok = jax.device_put(
            host, NamedSharding(_mesh(), P("i"))  # n/8 rows per device
        )
        ok.block_until_ready()
    out = aud.assert_not_replicated(n, itemsize)
    assert out["phases"] == ["sharded"]
    assert 0 < out["worst_fraction"] < 1.0
    assert out["threshold_bytes"] == pytest.approx(0.5 * n * itemsize)
    del ok


def test_gate_single_device_watermarks_pass():
    """With one device in the watermarks, "replicated vs sharded" is
    meaningless — the gate reports the bypass instead of tripping."""
    aud = MemoryAuditor(interval_s=0.005, source="live_arrays")
    aud._watermarks["fit"] = {
        "source": "live_arrays",
        "samples": 2,
        "max_device_bytes": 10**9,
        "total_bytes": 10**9,
        "per_device": {"cpu:0": 10**9},
        "wall_s": 0.1,
    }
    out = aud.assert_not_replicated(n=4096, itemsize=8)
    assert out["single_device"] is True
    assert out["worst_fraction"] == 0.0


def test_gate_refuses_to_pass_vacuously():
    aud = MemoryAuditor(source="live_arrays")
    with pytest.raises(RuntimeError, match="cannot pass vacuously"):
        aud.assert_not_replicated(n=10, itemsize=8)
    with pytest.raises(ValueError, match="never audited"):
        with aud.phase("real"):
            pass
        aud.assert_not_replicated(n=10, itemsize=8, phases=["imaginary"])


def test_gate_parameter_validation():
    aud = MemoryAuditor(source="live_arrays")
    with pytest.raises(ValueError, match="n must be"):
        aud.assert_not_replicated(n=0, itemsize=8)
    with pytest.raises(ValueError, match="itemsize"):
        aud.assert_not_replicated(n=10, itemsize=0)
    with pytest.raises(ValueError, match="slack"):
        aud.assert_not_replicated(n=10, itemsize=8, slack=0.0)


# -- heartbeats + watchdog --------------------------------------------------


def test_heartbeat_progress_monotone_and_eta(tmp_path):
    path = str(tmp_path / "hb.jsonl")
    tracer = Tracer(sinks=[JsonlSink(path)])
    hub = Heartbeats(tracer=tracer, heartbeat_s=1e-6)  # emit every beat
    with hub.task("boruvka", total=10) as t:
        for done in (2, 5, 4, 10):  # 4 then 10: progress must not regress
            t.beat(done)
    hub.close()
    tracer.close()
    beats = _events(tracer, "heartbeat")
    progress = [e.fields["progress"] for e in beats]
    assert progress[0] == 0.0 and progress[-1] == 1.0
    assert progress == sorted(progress)
    assert all(0.0 <= p <= 1.0 for p in progress)
    # ETA appears once progress is positive, and the trace satisfies the
    # validator's obs schemas (including the monotonicity cross-check).
    assert any("eta_s" in e.fields for e in beats)
    from scripts import check_trace

    events, errors = check_trace.validate_trace(path)
    assert errors == []
    assert sum(1 for e in events if e.get("stage") == "heartbeat") == len(beats)


def test_heartbeat_throttles_between_entry_and_exit():
    tracer = Tracer()
    hub = Heartbeats(tracer=tracer, heartbeat_s=60.0)
    with hub.task("ring", total=100) as t:
        for done in range(100):
            t.beat(done)
    hub.close()
    beats = _events(tracer, "heartbeat")
    assert len(beats) == 2  # entry + exit only; 100 beats all throttled
    assert beats[-1].fields["progress"] == 1.0


def test_task_ids_unique_across_tasks():
    hub = Heartbeats(heartbeat_s=0.5)
    ids = []
    for _ in range(3):
        with hub.task("p") as t:
            ids.append(t.task_id)
    hub.close()
    assert len(set(ids)) == 3


def test_watchdog_dumps_on_stalled_phase():
    """ISSUE acceptance: a deliberately stalled phase (fault harness's
    ``phase_stall`` site) triggers a stack dump within ``watchdog_s``."""

    class Counter:
        n = 0

        def inc(self):
            self.n += 1

    tracer = Tracer()
    counter = Counter()
    inject.install("phase_stall:count=1,delay_s=0.5")
    hub = Heartbeats(
        tracer=tracer, heartbeat_s=0.01, watchdog_s=0.1, stall_counter=counter
    )
    with hub.task("stalled_phase", total=2) as t:
        t.beat(1)  # injected 0.5 s stall before the liveness refresh
    hub.close()
    assert hub.stalls >= 1
    assert counter.n == hub.stalls
    stalls = _events(tracer, "watchdog_stall")
    assert len(stalls) == hub.stalls
    ev = stalls[0].fields
    assert ev["phases"] == ["stalled_phase"]
    assert ev["stalled_s"] > 0.1
    assert ev["threads"] >= 2
    # The dump contains actual Python stacks, including the stalled beat.
    assert "--- thread" in ev["stacks"]
    assert hub.state()["stalls"] == hub.stalls


def test_watchdog_zero_false_positives_on_healthy_loop():
    tracer = Tracer()
    hub = Heartbeats(tracer=tracer, heartbeat_s=0.01, watchdog_s=0.3)
    with hub.task("healthy", total=8) as t:
        for done in range(8):
            t.beat(done)
            threading.Event().wait(0.05)  # 0.4 s of work, beats every 50 ms
    hub.close()
    assert hub.stalls == 0
    assert _events(tracer, "watchdog_stall") == []


def test_watchdog_idle_is_not_a_stall():
    hub = Heartbeats(heartbeat_s=0.01, watchdog_s=0.05)
    threading.Event().wait(0.2)  # no active tasks: silence is fine
    hub.close()
    assert hub.stalls == 0


def test_heartbeat_knob_validation():
    with pytest.raises(ValueError, match="heartbeat_s"):
        Heartbeats(heartbeat_s=0.0)
    with pytest.raises(ValueError, match="watchdog_s"):
        Heartbeats(watchdog_s=-1.0)


def test_config_knobs_validated_eagerly():
    from hdbscan_tpu.config import HDBSCANParams

    with pytest.raises(ValueError, match="heartbeat_s"):
        HDBSCANParams(heartbeat_s=0.0)
    with pytest.raises(ValueError, match="watchdog_s"):
        HDBSCANParams(watchdog_s=-0.5)
    p = HDBSCANParams(heartbeat_s=2.5, watchdog_s=30.0)
    assert (p.heartbeat_s, p.watchdog_s) == (2.5, 30.0)


# -- the facade -------------------------------------------------------------


def test_facade_noops_when_uninstalled():
    assert obs.auditor() is None and obs.heartbeats() is None
    with obs.mem_phase("anything"):
        pass
    with obs.task("anything", total=3) as t:
        t.beat(1)  # the null task swallows beats
    obs.beat("anything", 1, total=2)
    assert obs.watchdog_state() is None
    with pytest.raises(RuntimeError, match="no MemoryAuditor installed"):
        obs.assert_not_replicated(n=10, itemsize=8)


def test_facade_scoped_install_restores():
    aud = MemoryAuditor(source="live_arrays")
    hub = Heartbeats(heartbeat_s=0.5)
    with obs.installed(auditor=aud, heartbeats=hub):
        assert obs.auditor() is aud and obs.heartbeats() is hub
        with obs.mem_phase("scoped"):
            pass
        assert obs.watchdog_state()["active_tasks"] == []
    assert obs.auditor() is None and obs.heartbeats() is None
    assert "scoped" in aud.watermark_table()


def test_facade_install_clear():
    aud = MemoryAuditor(source="live_arrays")
    obs.install(auditor=aud)
    assert obs.auditor() is aud
    assert obs.heartbeats() is None  # independent layers
    obs.install(heartbeats=Heartbeats(heartbeat_s=1.0))
    assert obs.auditor() is aud  # untouched by the second install
    obs.clear()
    assert obs.auditor() is None and obs.heartbeats() is None


# -- fleet span correlation -------------------------------------------------


def _router_span(rid, replied=True):
    return {
        "stage": "router_span",
        "request_id": rid,
        "route": "/predict",
        "policy": "consistent_hash",
        "replica": "replica_0",
        "status": 200 if replied else 503,
        "attempts": 1,
        "queue_s": 0.001,
        "wall_s": 0.002,
        "replied": replied,
    }


def _replica_span(rid, stage="request_span"):
    return {"stage": stage, "request_id": rid, "route": "/predict"}


def test_join_spans_complete_chain():
    router = [_router_span("r1-1"), _router_span("r1-2")]
    replicas = [_replica_span("r1-1"), _replica_span("r1-2", "request_shed")]
    out = obs.join_spans(router, replicas)
    assert out["complete"] is True
    assert out["matched"] == out["replied"] == 2
    assert out["orphans"] == [] and out["duplicates"] == []


def test_join_spans_flags_orphans_and_duplicates():
    router = [
        _router_span("r1-1"),
        _router_span("r1-2"),
        _router_span("r1-3", replied=False),  # 503: exempt from the join
    ]
    replicas = [_replica_span("r1-1"), _replica_span("r1-1")]
    out = obs.join_spans(router, replicas)
    assert out["complete"] is False
    assert out["duplicates"] == ["r1-1"]
    assert out["orphans"] == ["r1-2"]
    assert out["router_spans"] == 3 and out["replied"] == 2


def test_join_cli_mode(tmp_path):
    """check_trace.py --join validates files then requires a 100% join."""
    from scripts import check_trace

    router_path = str(tmp_path / "router.jsonl")
    replica_path = str(tmp_path / "replica.jsonl")
    rt = Tracer(sinks=[JsonlSink(router_path)])
    for ev in (_router_span("a"), _router_span("b", replied=False)):
        ev = dict(ev)
        rt(ev.pop("stage"), **ev)
    rt.close()
    rep = Tracer(sinks=[JsonlSink(replica_path)])
    rep(
        "request_span",
        request_id="a",
        route="/predict",
        status=200,
        rows=1,
        bucket=1,
        coalesced=1,
        generation=1,
        parse_s=0.0,
        queue_s=0.0,
        assemble_s=0.0,
        predict_s=0.0,
        respond_s=0.0,
    )
    rep.close()
    assert check_trace.join_fleet(router_path, [replica_path]) == 0
    # Drop the replica file's span: the replied router span goes orphan.
    empty = str(tmp_path / "empty.jsonl")
    et = Tracer(sinks=[JsonlSink(empty)])
    et("noop")
    et.close()
    assert check_trace.join_fleet(router_path, [empty]) == 1
