"""Mesh timelines, roofline attribution, and the flight recorder
(hdbscan_tpu/obs/timeline.py, roofline.py, flightrec.py).

Covers the ISSUE acceptance legs that fit in the unit lane, all on the
forced-8-device CPU mesh (conftest):

- every ``device_timeline`` event telescopes — ``compute_s + comm_s +
  host_s == wall_s`` within 1e-6 — both for synthetic rounds and through
  a real ring k-NN scan, and the written trace satisfies
  ``scripts/check_trace.py``'s new schemas;
- skew/straggler math: a device at >= ``skew_threshold``x the round
  median for ``straggler_rounds`` consecutive rounds is flagged, the
  counter increments per flagged round, streaks reset on recovery;
- a deliberately stalled device — the fault harness's ``phase_stall``
  site fires inside ``_per_device_walls`` — trips the detector within K
  rounds of a real ring scan;
- the flight recorder dumps a valid bundle on a watchdog stall (and
  exactly zero bundles on a healthy run), validated by
  ``scripts/check_flight.py``;
- ``JsonlSink`` rotation keeps at most two files with exactly contiguous
  ``seq`` across the boundary, and ``check_trace.py`` validates the pair
  as one logical trace.
"""

import glob
import json
import math
import os
import threading
import time

import jax
import numpy as np
import pytest

from hdbscan_tpu import obs
from hdbscan_tpu.fault import inject
from hdbscan_tpu.obs.audit import MemoryAuditor
from hdbscan_tpu.obs.flightrec import FlightRecorder
from hdbscan_tpu.obs.heartbeat import Heartbeats
from hdbscan_tpu.obs.roofline import (
    COMM_BOUND_FRAC,
    classify_bound,
    roofline_section,
)
from hdbscan_tpu.obs.timeline import (
    MODEL_COMM_BYTES_S,
    TimelineRecorder,
    _split_exec,
)
from hdbscan_tpu.parallel.mesh import get_mesh
from hdbscan_tpu.parallel.ring import ring_knn_core_distances
from hdbscan_tpu.utils import flops as flops_mod
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
from scripts import check_flight, check_trace

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="timelines need a multi-device mesh"
)

TILES = dict(row_tile=64, col_tile=128)


@pytest.fixture(autouse=True)
def _clean_installs():
    """Never leak a process-global recorder/fault-plan across tests."""
    yield
    obs.clear()
    inject.clear()


def _events(tracer, stage):
    return [e for e in tracer.events if e.name == stage]


def _blobs(n, d=5, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=6.0, size=(4, d))
    pts = np.concatenate(
        [rng.normal(c, 0.8, size=(n // 4, d)) for c in centers]
        + [rng.normal(size=(n - 4 * (n // 4), d))]
    )
    return pts.astype(np.float64)


class _Counter:
    """hdbscan_tpu_straggler_flags_total test double."""

    def __init__(self):
        self.calls = []

    def inc(self, value=1.0, **labels):
        self.calls.append((value, labels))


# -- the cost-model split ---------------------------------------------------


def test_split_exec_telescopes_exactly():
    comp, comm = _split_exec(0.5, comm_bytes=MODEL_COMM_BYTES_S,
                             flops=flops_mod.PEAK_FLOPS)
    # Equal model times -> an even split; the halves sum back exactly.
    assert comp == pytest.approx(0.25)
    assert comm == pytest.approx(0.25)
    assert comp + comm == 0.5


def test_split_exec_degenerate_cases():
    assert _split_exec(0.0, 1e9, 1e12) == (0.0, 0.0)
    # No model signal at all: the whole wall is compute, never NaN.
    assert _split_exec(0.3, 0, 0.0) == (0.3, 0.0)
    # Pure comm: the whole wall attributes to the ring.
    comp, comm = _split_exec(0.3, 1e9, 0.0)
    assert comp == 0.0 and comm == 0.3


# -- record_round: telescoping + skew ---------------------------------------


def test_record_round_events_telescope():
    tracer = Tracer()
    rec = TimelineRecorder(trace=tracer)
    walls = [(d, 0.010 + 0.001 * d) for d in range(8)]
    stats = rec.record_round(
        "ring_knn_scan", 0, walls, upload_s=0.004, fetch_s=0.003,
        comm_bytes=7 * 2**20, flops=4.2e9,
    )
    evs = _events(tracer, "device_timeline")
    assert len(evs) == 8
    assert {e.fields["device"] for e in evs} == set(range(8))
    for e in evs:
        f = e.fields
        assert f["attribution"] == "model"
        assert f["comm_bytes"] == 7 * 2**20
        total = f["compute_s"] + f["comm_s"] + f["host_s"]
        assert math.isclose(total, e.wall_s, rel_tol=0.0, abs_tol=1e-6)
        # Host segments bracket the dispatch: the same measured value
        # lands on every device's row.
        assert f["host_s"] == pytest.approx(0.007, abs=1e-9)
    assert stats["skew"] == pytest.approx(0.017 / 0.0135, rel=1e-4)
    assert stats["flagged"] == []


def test_record_round_empty_is_noop():
    rec = TimelineRecorder()
    assert rec.record_round("p", 0, []) is None
    assert rec.phase_table() == {}


def test_phase_table_accumulates_and_derives():
    rec = TimelineRecorder()
    for rnd in range(3):
        rec.record_round("scan", rnd, [(d, 0.010) for d in range(8)],
                         comm_bytes=2**20, flops=1e9)
    tbl = rec.phase_table()["scan"]
    assert tbl["rounds"] == 3
    assert tbl["devices"] == 8
    assert tbl["comm_bytes"] == 3 * 8 * 2**20
    assert tbl["flops"] == pytest.approx(3e9)
    assert tbl["wall_s"] == pytest.approx(0.030, abs=1e-8)
    assert 0.0 <= tbl["comm_frac"] <= 1.0
    assert tbl["skew"] >= 1.0


def test_straggler_trips_after_k_rounds_and_resets():
    tracer = Tracer()
    counter = _Counter()
    rec = TimelineRecorder(skew_threshold=2.0, straggler_rounds=3,
                           straggler_counter=counter, trace=tracer)

    def round_walls(slow_dev=None):
        return [
            (d, 0.030 if d == slow_dev else 0.010) for d in range(8)
        ]

    # Two slow rounds: streak 2 < K, nothing fires.
    for rnd in range(2):
        stats = rec.record_round("scan", rnd, round_walls(slow_dev=7))
        assert stats["flagged"] == []
    assert _events(tracer, "straggler_flag") == []
    # Third consecutive slow round: flag fires, and keeps firing per round.
    for rnd in (2, 3):
        stats = rec.record_round("scan", rnd, round_walls(slow_dev=7))
        assert stats["flagged"] == [7]
    flags = _events(tracer, "straggler_flag")
    assert [e.fields["streak"] for e in flags] == [3, 4]
    for e in flags:
        f = e.fields
        assert f["device"] == 7
        assert f["ratio"] >= f["threshold"] == 2.0
        assert e.wall_s >= f["median_s"] > 0
    assert counter.calls == [
        (1.0, {"device": "7"}), (1.0, {"device": "7"}),
    ]
    # A healthy round resets the streak; the next slow round starts at 1.
    assert rec.record_round("scan", 4, round_walls())["flagged"] == []
    assert rec.record_round("scan", 5, round_walls(slow_dev=7))["flagged"] == []
    st = rec.state()
    assert st["flags_total"] == 2
    assert st["flags"] == {"7": 2}
    assert st["streaks"] == {"7": 1}
    assert st["rounds"] == 6


def test_straggler_needs_multiple_devices_and_positive_median():
    rec = TimelineRecorder(skew_threshold=1.0, straggler_rounds=1)
    # One device can't straggle relative to itself.
    assert rec.record_round("p", 0, [(0, 5.0)])["flagged"] == []
    # A zero median (all-zero walls) never divides, never flags.
    stats = rec.record_round("p", 1, [(0, 0.0), (1, 0.0)])
    assert stats["flagged"] == [] and stats["skew"] == 1.0


def test_recorder_knob_validation():
    with pytest.raises(ValueError, match="skew_threshold"):
        TimelineRecorder(skew_threshold=0.5)
    with pytest.raises(ValueError, match="straggler_rounds"):
        TimelineRecorder(straggler_rounds=0)


# -- through a real ring scan -----------------------------------------------


def test_ring_scan_timeline_telescopes_and_validates(tmp_path):
    """ISSUE acceptance: a real ring k-NN scan produces device_timeline
    rows for every mesh device that telescope within 1e-6, and the trace
    passes check_trace's new schemas."""
    path = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(path)])
    rec = TimelineRecorder(trace=tracer)
    data = _blobs(256)
    with obs.installed(timeline=rec):
        ring_knn_core_distances(data, 5, "euclidean", mesh=get_mesh(),
                                trace=tracer, **TILES)
    tracer.close()
    evs = _events(tracer, "device_timeline")
    assert {e.fields["device"] for e in evs} == {
        d.id for d in jax.devices()
    }
    for e in evs:
        f = e.fields
        total = f["compute_s"] + f["comm_s"] + f["host_s"]
        assert math.isclose(total, e.wall_s, rel_tol=0.0, abs_tol=1e-6)
        assert f["comm_bytes"] > 0  # the ring moved real panel bytes
    # The summary event carries the round's skew stats.
    summary = _events(tracer, "ring_knn_scan")[0].fields
    assert summary["skew"] >= 1.0
    assert summary["max_device_wall_s"] >= summary["median_device_wall_s"]
    _, errors = check_trace.validate_trace(path)
    assert errors == []
    assert rec.phase_table()["ring_knn_scan"]["rounds"] == 1


def test_injected_straggler_flagged_within_k_rounds():
    """ISSUE acceptance: the phase_stall site deterministically inflates
    the highest-id device, and the detector flags it within K rounds."""
    mesh = get_mesh()
    data = _blobs(256)
    # Warm the jit cache first so compile wall can't drown the stall.
    ring_knn_core_distances(data, 5, "euclidean", mesh=mesh, **TILES)
    tracer = Tracer()
    counter = _Counter()
    rec = TimelineRecorder(skew_threshold=2.0, straggler_rounds=3,
                           straggler_counter=counter, trace=tracer)
    with obs.installed(timeline=rec):
        inject.install("phase_stall:count=3,delay_s=0.25")
        for _ in range(3):
            ring_knn_core_distances(data, 5, "euclidean", mesh=mesh, **TILES)
    flags = _events(tracer, "straggler_flag")
    slowest = max(d.id for d in jax.devices())
    assert len(flags) == 1  # the streak reaches K on the third round
    assert flags[0].fields["device"] == slowest
    assert flags[0].fields["streak"] == 3
    assert counter.calls == [(1.0, {"device": str(slowest)})]
    assert rec.state()["flags"] == {str(slowest): 1}


# -- roofline ---------------------------------------------------------------


def test_classify_bound():
    ridge = flops_mod.PEAK_FLOPS / 819e9
    assert classify_bound(None, ridge, 0.9) == "comm"
    assert classify_bound(ridge * 2, ridge, COMM_BOUND_FRAC) == "comm"
    assert classify_bound(ridge * 2, ridge, 0.1) == "compute"
    assert classify_bound(ridge / 2, ridge, 0.1) == "memory"
    assert classify_bound(None, ridge, None) == "memory"


def test_roofline_section_joins_timeline_and_flops():
    rec = TimelineRecorder()
    rec.record_round("scan", 0, [(d, 0.010) for d in range(8)],
                     comm_bytes=2**20, flops=1e9)
    agg = {"scan": {"gflops": 1.0, "gbytes": 0.5, "wall_s": 0.010}}
    sec = roofline_section(agg, rec.phase_table(), tags=["cpu_smoke"])
    assert sec["tags"] == ["cpu_smoke"]
    assert sec["ridge_intensity"] > 0
    row = sec["phases"]["scan"]
    assert row["bound"] in ("compute", "memory", "comm")
    assert row["arithmetic_intensity"] == pytest.approx(2.0)
    assert row["achieved_gflops_s"] == pytest.approx(100.0, rel=1e-3)
    assert row["mfu"] > 0
    assert row["rounds"] == 1 and row["devices"] == 8


def test_roofline_section_empty_is_none():
    assert roofline_section({}, {}) is None
    # A phase with neither flops, bytes, nor timeline row is skipped.
    assert roofline_section({"p": {"gflops": 0.0, "gbytes": 0.0}}) is None


# -- flight recorder --------------------------------------------------------


def test_flight_dumps_on_watchdog_stall(tmp_path):
    """ISSUE acceptance: a watchdog stall auto-dumps one self-contained
    bundle that check_flight validates green."""
    flight_dir = str(tmp_path / "flight")
    tracer = Tracer()
    flight = FlightRecorder(flight_dir, manifest={"argv": ["test"]},
                            tracer=tracer)
    tracer.add_sink(flight)
    inject.install("phase_stall:count=1,delay_s=0.5")
    hub = Heartbeats(tracer=tracer, heartbeat_s=0.01, watchdog_s=0.1)
    with obs.installed(heartbeats=hub):
        with hub.task("stalled_phase", total=2) as t:
            t.beat(1)  # injected 0.5 s stall before the liveness refresh
    hub.close()
    assert hub.stalls >= 1
    assert len(flight.dumps) == len(glob.glob(
        os.path.join(flight_dir, "flight-*.json")
    )) == hub.stalls
    bundle, errors = check_flight.validate_bundle(flight.dumps[0])
    assert errors == []
    assert bundle["reason"] == "watchdog_stall"
    assert bundle["manifest"] == {"argv": ["test"]}
    assert any(r["stage"] == "watchdog_stall" for r in bundle["events"])
    assert all(r["stage"] == "heartbeat" for r in bundle["heartbeats"])
    assert "--- thread" in bundle["stacks"]
    assert check_flight.main([flight_dir]) == 0


def test_flight_zero_dumps_on_healthy_run(tmp_path):
    flight_dir = str(tmp_path / "flight")
    tracer = Tracer()
    flight = FlightRecorder(flight_dir, tracer=tracer)
    tracer.add_sink(flight)
    hub = Heartbeats(tracer=tracer, heartbeat_s=0.01, watchdog_s=0.5)
    with hub.task("healthy", total=4) as t:
        for done in range(4):
            t.beat(done)
            threading.Event().wait(0.02)
    hub.close()
    assert flight.dumps == []
    # An armed recorder on a healthy run leaves no filesystem trace at all.
    assert not os.path.exists(flight_dir)
    assert check_flight.main([str(tmp_path), "--allow-empty"]) == 0
    assert check_flight.main([str(tmp_path)]) == 1  # no bundles = not proof


def test_flight_manual_dump_emits_event(tmp_path):
    tracer = Tracer()
    flight = FlightRecorder(str(tmp_path), tracer=tracer)
    tracer.add_sink(flight)
    tracer("some_phase", wall_s=0.25, detail="x")
    # An installed auditor contributes its watermark TABLE (phase -> row
    # dict, not a list) to the bundle — the validator must accept it
    # (caught live on a replication_gate dump from the sharded CLI).
    aud = MemoryAuditor(source="live_arrays", interval_s=0.005)
    with obs.installed(auditor=aud):
        with obs.mem_phase("wm_phase"):
            time.sleep(0.03)
        path = flight.dump("manual", extra={"why": "test"})
    bundle, errors = check_flight.validate_bundle(path)
    assert errors == []
    assert isinstance(bundle["watermarks"], dict)
    assert "wm_phase" in bundle["watermarks"]
    assert bundle["extra"] == {"why": "test"}
    assert bundle["events_seen"] >= 1
    evs = _events(tracer, "flight_dump")
    assert len(evs) == 1
    assert evs[0].fields["reason"] == "manual"
    assert evs[0].fields["path"] == path
    with pytest.raises(ValueError, match="reason"):
        flight.dump("because")


def test_flight_ring_is_bounded(tmp_path):
    tracer = Tracer()
    flight = FlightRecorder(str(tmp_path), capacity=16, heartbeat_tail=2,
                            tracer=tracer)
    tracer.add_sink(flight)
    for i in range(64):
        tracer("spam", wall_s=0.001, i=i)
    snap = flight.snapshot()
    assert len(snap["events"]) == 16
    assert snap["events_seen"] == 64
    assert snap["events"][-1]["i"] == 63  # newest survive, oldest dropped
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(str(tmp_path), capacity=8)


# -- trace rotation ---------------------------------------------------------


def test_jsonl_sink_rotates_and_check_trace_accepts(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    sink = JsonlSink(path, rotate_bytes=4096)
    tracer = Tracer(sinks=[sink])
    for i in range(200):
        tracer("rotation_filler", wall_s=0.001, i=i)
    tracer.close()
    assert sink.rotations >= 1
    assert os.path.exists(path) and os.path.exists(path + ".1")
    # At most two files ever: the live file and one rotated predecessor.
    assert sorted(glob.glob(path + "*")) == [path, path + ".1"]
    assert os.path.getsize(path + ".1") <= 4096
    # seq is exactly contiguous across the boundary: nothing was lost.
    with open(path + ".1") as f:
        rotated = [json.loads(line) for line in f]
    with open(path) as f:
        live = [json.loads(line) for line in f]
    assert live[0]["seq"] == rotated[-1]["seq"] + 1
    seqs = [e["seq"] for e in rotated + live]
    assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
    events, errors = check_trace.validate_trace(path)
    assert errors == []
    assert len(events) == len(rotated) + len(live)


def test_jsonl_sink_rotation_off_by_default(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(path)])
    for i in range(200):
        tracer("rotation_filler", wall_s=0.001, i=i)
    tracer.close()
    assert not os.path.exists(path + ".1")


def test_check_trace_rejects_rotated_seq_gap(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    sink = JsonlSink(path, rotate_bytes=4096)
    tracer = Tracer(sinks=[sink])
    for i in range(200):
        tracer("rotation_filler", wall_s=0.001, i=i)
    tracer.close()
    with open(path) as f:
        lines = f.readlines()
    with open(path, "w") as f:  # drop the live file's first line
        f.writelines(lines[1:])
    _, errors = check_trace.validate_trace(path)
    assert any("rotated set discontinuous" in e for e in errors)


# -- validator schemas ------------------------------------------------------


def _trace_line(stage, **fields):
    return dict({"schema": "hdbscan-tpu-trace/1", "stage": stage,
                 "seq": 1, "process": 1, "wall_s": 0.01}, **fields)


def _write_trace(tmp_path, rows):
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        for i, row in enumerate(rows):
            row["seq"] = i + 1
            f.write(json.dumps(row) + "\n")
    return path


def test_check_trace_flags_broken_telescoping(tmp_path):
    good = _trace_line(
        "device_timeline", phase="scan", round=0, device=0, wall_s=0.010,
        compute_s=0.006, comm_s=0.003, host_s=0.001, comm_bytes=1024,
        attribution="model",
    )
    bad = dict(good, compute_s=0.009)  # sums to 0.013 != 0.010
    _, errors = check_trace.validate_trace(_write_trace(tmp_path, [good]))
    assert errors == []
    _, errors = check_trace.validate_trace(_write_trace(tmp_path, [bad]))
    assert any("telescope" in e for e in errors)


def test_check_trace_flags_round_skip_and_allows_reset(tmp_path):
    def tl(rnd):
        return _trace_line(
            "device_timeline", phase="scan", round=rnd, device=0,
            wall_s=0.010, compute_s=0.010, comm_s=0.0, host_s=0.0,
            comm_bytes=0, attribution="model",
        )

    # 0, 1, 0 (a fresh scanner resets) is fine; 0, 2 skipped a round.
    _, errors = check_trace.validate_trace(
        _write_trace(tmp_path, [tl(0), tl(1), tl(0)])
    )
    assert errors == []
    _, errors = check_trace.validate_trace(
        _write_trace(tmp_path, [tl(0), tl(2)])
    )
    assert any("skipped ahead" in e for e in errors)


def test_check_trace_straggler_flag_schema(tmp_path):
    good = _trace_line(
        "straggler_flag", phase="scan", round=3, device=7, streak=3,
        wall_s=0.030, median_s=0.010, ratio=3.0, threshold=2.0,
    )
    _, errors = check_trace.validate_trace(_write_trace(tmp_path, [good]))
    assert errors == []
    bad = dict(good, ratio=1.5)  # flagged below its own threshold
    _, errors = check_trace.validate_trace(_write_trace(tmp_path, [bad]))
    assert any("below threshold" in e for e in errors)


def test_check_trace_flight_dump_schema(tmp_path):
    good = _trace_line(
        "flight_dump", reason="manual", path="/tmp/flight-1-000-manual.json",
        events=12,
    )
    _, errors = check_trace.validate_trace(_write_trace(tmp_path, [good]))
    assert errors == []
    bad = dict(good, reason="felt_like_it")
    _, errors = check_trace.validate_trace(_write_trace(tmp_path, [bad]))
    assert any("reason" in e for e in errors)


# -- audit: zero-sample phases stay honest ----------------------------------


def test_audit_zero_sample_phase_gets_sampled_false_row():
    """Satellite fix: a phase whose sampling failed entirely still lands
    in the watermark table — ``sampled: false``, not a missing key."""
    tracer = Tracer()
    aud = MemoryAuditor(tracer=tracer, interval_s=0.005,
                        source="live_arrays")
    # Simulate the sampler dying mid-run: memory_stats is unavailable on
    # CPU, so every sample attempt from here on raises (and is swallowed
    # best-effort by the phase bracket).
    aud._source_pref = "memory_stats"
    with aud.phase("unsampled"):
        pass
    row = aud.watermark_table()["unsampled"]
    assert row["sampled"] is False
    assert row["samples"] == 0
    assert row["max_device_bytes"] == 0
    peak = _events(tracer, "mem_phase_peak")[0].fields
    assert peak["sampled"] is False and peak["samples"] == 0
    # The zero row is schema-valid (check_trace accepts sampled: false).
    errors = check_trace._check_obs("t", 1, "mem_phase_peak", dict(
        peak, schema="hdbscan-tpu-trace/1", stage="mem_phase_peak",
    ))
    assert errors == []
