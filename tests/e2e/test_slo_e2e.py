"""Tier-1 observability round-trip (README "Observability"): a small fit
is served over HTTP, the load generator drives closed-loop mixed-size
traffic at it, ``/metrics`` is scraped twice with traffic in between, and
EVERY artifact passes its validator — the exposition + monotonicity checks
of ``scripts/check_metrics.py``, the ``request_span`` schema +
telescoping-segments + unique-request-id checks of
``scripts/check_trace.py``, the report round-trip, enriched ``/healthz``,
and the histogram-vs-raw p99 one-bucket-width accuracy contract.

The sustained duration-based variant rides the same harness marked
``slow`` (excluded from tier-1).
"""

import json
import urllib.request

import numpy as np
import pytest

from benchmarks import loadgen
from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import hdbscan
from hdbscan_tpu.serve.server import ClusterServer
from hdbscan_tpu.utils import telemetry
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
from scripts import check_metrics, check_trace
from tests.conftest import make_blobs

MIX = ((1, 0.5), (7, 0.3), (24, 0.2))


@pytest.fixture(scope="module")
def served():
    """One 300-pt fit + live HTTP server + a closed-loop load run, shared
    by every assertion in the module: (base_url, result, scrapes, paths)."""
    rng = np.random.default_rng(7)
    data, _ = make_blobs(rng, n=300, d=3, centers=3)
    params = HDBSCANParams(min_points=8, min_cluster_size=8)
    model = hdbscan.fit(data, params).to_cluster_model(data, params)

    import tempfile, os

    tmp = tempfile.mkdtemp(prefix="slo_e2e_")
    trace_path = os.path.join(tmp, "trace.jsonl")
    report_path = os.path.join(tmp, "report.json")
    tracer = Tracer(sinks=[JsonlSink(trace_path, static={"process": 0})])
    srv = ClusterServer(model, max_batch=32, port=0, tracer=tracer).start()
    base = f"http://127.0.0.1:{srv.port}"

    def sampler(k):
        return data[rng.integers(0, len(data), k)] + rng.normal(0, 0.02, (k, 3))

    result = loadgen.run_load(
        loadgen.http_predict_submitter(base, sampler),
        mode="closed", concurrency=3, batch_mix=MIX,
        requests=40, warmup_requests=4,
    )
    with urllib.request.urlopen(base + "/metrics") as resp:
        scrape1 = resp.read().decode()
        ctype = resp.headers.get("Content-Type", "")
    # more traffic between the scrapes so monotone counters actually move
    more = loadgen.run_load(
        loadgen.http_predict_submitter(base, sampler),
        mode="closed", concurrency=2, batch_mix=MIX, requests=10,
    )
    with urllib.request.urlopen(base + "/metrics") as resp:
        scrape2 = resp.read().decode()
    with urllib.request.urlopen(base + "/healthz") as resp:
        health = json.loads(resp.read())
    srv.close()
    tracer.close()
    telemetry.write_report(
        report_path,
        telemetry.build_report(tracer, manifest=telemetry.run_manifest(None)),
    )
    yield {
        "base": base, "result": result, "more": more, "ctype": ctype,
        "scrapes": (scrape1, scrape2), "health": health,
        "trace": trace_path, "report": report_path,
    }


def test_load_ran_clean(served):
    r = served["result"]
    assert r.errors == 0
    assert r.requests == 40 and r.warmup_requests == 4
    assert r.rows >= 40  # mixed sizes, min 1 row each
    assert served["more"].errors == 0


def test_metrics_scrapes_pass_validator(served):
    s1, s2 = served["scrapes"]
    assert served["ctype"].startswith("text/plain")
    p1, errs1 = check_metrics.validate_exposition(s1, "scrape1")
    p2, errs2 = check_metrics.validate_exposition(s2, "scrape2")
    assert errs1 == [] and errs2 == []
    assert check_metrics.check_monotonic(p1, p2) == []
    # the serving families are actually present with traffic in them
    key = ("hdbscan_tpu_requests_total",
           (("route", "/predict"), ("status", "200")))
    assert p1["samples"][key] >= 44  # 40 recorded + 4 warmup
    assert p2["samples"][key] >= p1["samples"][key] + 10
    lat_count = [v for (n, _), v in p2["samples"].items()
                 if n == "hdbscan_tpu_request_latency_seconds_count"]
    assert sum(lat_count) >= 54
    assert any(n == "hdbscan_tpu_predict_batch_rows_count"
               for (n, _) in p2["samples"])


def test_request_spans_pass_trace_validator(served):
    events, errors = check_trace.validate_trace(served["trace"])
    assert errors == []
    spans = [e for e in events if e.get("stage") == "request_span"]
    # every recorded + warmup request got exactly one span
    assert len(spans) == 54
    rids = {e["request_id"] for e in spans}
    assert len(rids) == len(spans)
    for e in spans:
        segs = sum(e[k] for k in check_trace.SPAN_SEGMENTS)
        assert abs(segs - e["wall_s"]) <= check_trace.WALL_TOLERANCE
    # coalescing happened at least once under 3-way concurrency, or not —
    # but the field is always a positive int and buckets are pow2
    assert all(e["coalesced"] >= 1 and e["bucket"] >= 1 for e in spans)


def test_report_round_trips_against_trace(served):
    events, _ = check_trace.validate_trace(served["trace"])
    report, errors = check_trace.validate_report(
        served["report"], trace_events=events
    )
    assert errors == []
    spans_section = report["request_spans"]
    assert spans_section["count"] == 54
    assert spans_section["rows_per_s"] > 0
    assert set(spans_section["segments_s"]) == set(check_trace.SPAN_SEGMENTS)


def test_healthz_enriched(served):
    h = served["health"]
    assert h["status"] == "ok"
    assert h["uptime_s"] > 0
    assert h["in_flight"] == 1  # the /healthz request itself, nothing else
    pred = h["requests"]["/predict"]
    assert pred["requests"] >= 54 and pred["errors"] == 0
    # the /metrics scrape itself is counted under its route
    assert h["requests"]["/metrics"]["requests"] >= 1


def test_hist_p99_within_one_bucket_of_raw(served):
    r = served["result"]
    assert r.quantiles_consistent(0.99)
    assert r.quantiles_consistent(0.5)
    pct = r.percentiles()
    assert pct["p50_s"] <= pct["p99_s"] <= pct["p999_s"] <= pct["max_s"]


@pytest.mark.slow
def test_sustained_slo_window():
    """Duration-based sustained variant of the same harness (not tier-1):
    8s closed-loop + open-loop Poisson secondary, SLO verdict attained."""
    rng = np.random.default_rng(11)
    data, _ = make_blobs(rng, n=300, d=3, centers=3)
    params = HDBSCANParams(min_points=8, min_cluster_size=8)
    model = hdbscan.fit(data, params).to_cluster_model(data, params)
    srv = ClusterServer(model, max_batch=32, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"

    def sampler(k):
        return data[rng.integers(0, len(data), k)] + rng.normal(0, 0.02, (k, 3))

    try:
        submit = loadgen.http_predict_submitter(base, sampler)
        closed = loadgen.run_load(
            submit, mode="closed", concurrency=4, batch_mix=MIX,
            duration_s=8.0, warmup_s=1.0,
        )
        opened = loadgen.run_load(
            submit, mode="open", concurrency=4, rate_rps=40.0,
            duration_s=4.0, warmup_s=0.5,
        )
    finally:
        srv.close()
    assert closed.errors == 0 and opened.errors == 0
    pct = closed.percentiles()
    verdict = telemetry.slo_verdict(
        {
            "p99_s": pct["p99_s"],
            "rows_per_s": closed.rows_per_s(),
            "error_rate": 0.0,
        },
        {
            "p99_s": {"max": 1.0},
            "rows_per_s": {"min": 50.0},
            "error_rate": {"max": 0.0},
        },
    )
    assert verdict["ok"], verdict
    assert closed.quantiles_consistent(0.99)
