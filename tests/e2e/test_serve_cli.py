"""End-to-end serving round-trips: fit --model-out -> predict via the CLI
(with trace/report validation through scripts/check_trace.py), and the HTTP
server against live requests."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from hdbscan_tpu.cli import main
from scripts import check_trace


def _write_blobs(tmp_path, n_per=60, centers=((0, 0, 0), (6, 6, 6), (0, 8, 0))):
    rng = np.random.default_rng(5)
    pts = np.concatenate(
        [rng.normal(np.asarray(c, float), 0.4, (n_per, 3)) for c in centers]
    )
    path = tmp_path / "blobs.txt"
    np.savetxt(path, pts, fmt="%.6f", delimiter=",")
    return str(path), pts


def test_fit_predict_cli_roundtrip(tmp_path):
    """fit --model-out -> predict reproduces the partition, and the predict
    trace/report pass the validator (predict_batch invariants + latency
    percentile cross-check)."""
    data_path, _ = _write_blobs(tmp_path)
    model_path = str(tmp_path / "model.npz")
    pred_path = str(tmp_path / "pred.csv")
    trace = str(tmp_path / "trace.jsonl")
    report = str(tmp_path / "report.json")

    rc = main(
        ["fit", f"file={data_path}", "minPts=8", "minClSize=8",
         f"out_dir={tmp_path}", "--model-out", model_path]
    )
    assert rc == 0

    rc = main(
        ["predict", "--model", model_path, "--points", data_path,
         "--out", pred_path, "predict_batch=32",
         "--trace-out", trace, "--report", report]
    )
    assert rc == 0

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    rep, errors = check_trace.validate_report(report, trace_events=events)
    assert not errors, errors

    stages = {e["stage"] for e in events}
    assert {"model_load", "load_points", "predict_warmup",
            "predict_batch"} <= stages
    batches = [e for e in events if e["stage"] == "predict_batch"]
    assert all(e["bucket"] in (8, 16, 32) for e in batches)
    assert "predict_latency" in rep
    assert rep["predict_latency"]["count"] == len(batches)
    assert rep["predict_latency"]["rows"] == 180

    fit_labels = np.loadtxt(
        tmp_path / "blobs_partition.csv", delimiter=","
    ).ravel().astype(int)
    pred = np.loadtxt(pred_path, delimiter=",", skiprows=1)
    mask = fit_labels > 0
    np.testing.assert_array_equal(pred[:, 0].astype(int)[mask], fit_labels[mask])
    assert np.all(pred[:, 1] >= 0) and np.all(pred[:, 1] <= 1)


def test_predict_cli_refuses_bad_model(tmp_path):
    data_path, _ = _write_blobs(tmp_path, n_per=20)
    missing = str(tmp_path / "nope.npz")
    assert main(["predict", "--model", missing, "--points", data_path]) == 2
    assert main(["predict", "--model", missing]) == 2  # --points required


def test_http_server_roundtrip(tmp_path):
    from hdbscan_tpu import HDBSCANParams
    from hdbscan_tpu.models import hdbscan
    from hdbscan_tpu.serve.server import ClusterServer

    _, pts = _write_blobs(tmp_path)
    params = HDBSCANParams(min_points=8, min_cluster_size=8)
    result = hdbscan.fit(pts, params)
    model = result.to_cluster_model(pts, params)

    with ClusterServer(model, max_batch=32, port=0).start() as srv:
        url = f"http://127.0.0.1:{srv.port}"
        health = json.loads(urllib.request.urlopen(url + "/healthz").read())
        assert health["status"] == "ok"
        assert health["model"]["n_train"] == len(pts)
        assert health["warmup"]["buckets"] == [8, 16, 32]

        body = json.dumps({"points": pts[:10].tolist()}).encode()
        resp = json.loads(
            urllib.request.urlopen(
                urllib.request.Request(url + "/predict", data=body)
            ).read()
        )
        np.testing.assert_array_equal(
            resp["labels"], np.asarray(result.labels)[:10]
        )
        assert len(resp["probabilities"]) == 10
        assert len(resp["outlier_scores"]) == 10

        body = json.dumps(
            {"points": [pts[0].tolist()], "membership": True}
        ).encode()
        resp = json.loads(
            urllib.request.urlopen(
                urllib.request.Request(url + "/predict", data=body)
            ).read()
        )
        assert resp["selected_ids"] == model.selected_ids.tolist()
        assert len(resp["membership"][0]) == len(model.selected_ids)

        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                urllib.request.Request(
                    url + "/predict", data=b'{"points": [[1, 2]]}'
                )
            )
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/unknown")
        assert e.value.code == 404
    assert srv.batcher.stats["rows"] == 10


def test_legacy_bare_invocation_still_fits(tmp_path):
    """The reference-compatible key=value form (no subcommand) keeps working."""
    data_path, _ = _write_blobs(tmp_path, n_per=20)
    rc = main([f"file={data_path}", "minPts=4", "minClSize=4",
               f"out_dir={tmp_path}"])
    assert rc == 0
    assert (tmp_path / "blobs_partition.csv").exists()


def test_fleet_cli_validates_args(capsys):
    """The fleet subcommand (README "Fleet") fails fast on a missing
    --model or a bad fleet knob — exit 2 with the reason, before any
    replica is spawned."""
    from hdbscan_tpu.cli import HELP

    assert main(["fleet"]) == 2
    assert "--model" in capsys.readouterr().err
    assert main(["fleet", "--model", "m.npz", "fleet_policy=round_robin"]) == 2
    err = capsys.readouterr().err
    assert "fleet_policy" in err and "round_robin" in err
    assert main(["fleet", "--model", "m.npz", "fleet_replicas=0"]) == 2
    assert "fleet_replicas" in capsys.readouterr().err
    # the help text documents the subcommand and its knobs
    for needle in ("fleet", "fleet_replicas=N", "fleet_policy=",
                   "--tenants-dir DIR", "tenant_quota=F"):
        assert needle in HELP, f"HELP missing {needle!r}"


def test_fleet_cli_missing_model_fails_fast(tmp_path):
    """A model path that doesn't exist dies at replica startup with exit
    2, not a hang: the router's startup deadline converts a replica that
    never binds its port into a loud error."""
    rc = main([
        "fleet", "--model", str(tmp_path / "nope.npz"),
        "fleet_replicas=1", "fleet_health_interval=0.1",
    ])
    assert rc == 2
