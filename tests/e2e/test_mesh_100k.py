"""Production-budget engaged-tiling mesh leg at >= 100k points (VERDICT r4
item 7 / r3 item 8b).

The dryrun's 20k engaged-tiling leg lowers ``_BATCH_SLOT_BUDGET`` /
``_DISPATCH_ROWS`` so multi-chunk window dispatch engages at toy sizes. This
test runs the boundary-mode pipeline at 131,072 points on the 8-device
virtual mesh with PRODUCTION budgets untouched, asserting mesh == unsharded
labels — the window machinery (probe phase, candidate windows, device-side
best-k merges, pruned glue rounds) all engage at this size under the real
dispatch parameters.

Honest scope note: multi-CHUNK window dispatch (> 1 pow2 chunk per rescan)
at the production ``_BATCH_SLOT_BUDGET`` of 2^21 row slots mathematically
requires > ~2M boundary-row tile slots, which no CPU-mesh test can afford;
that axis is covered by (a) the forced-chunk-split exactness test
(tests/e2e/test_mr_pipeline.py, lowered budget, same code path) and (b) the
real-chip multi-M campaign rows whose ARI-vs-truth pins end-to-end
correctness (benchmarks/boundary_eval_r*.jsonl).

Slow tier: ~minutes on the CPU mesh — gated behind HDBSCAN_TPU_SLOW=1 so
the default suite stays fast. The DOCUMENTED entry point is the slow lane
(README "Testing"), which self-provisions the 8-device virtual mesh and
runs this test body after the dry run::

    python -c "import __graft_entry__ as g; g.dryrun_multichip(8, slow=True)"

Direct pytest invocation still works for iterating on the test itself:
    HDBSCAN_TPU_SLOW=1 python -m pytest tests/e2e/test_mesh_100k.py -q
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("HDBSCAN_TPU_SLOW"),
    reason="slow tier: set HDBSCAN_TPU_SLOW=1 (production-budget 131k mesh leg)",
)


def test_mesh_boundary_131k_production_budgets():
    import jax

    from hdbscan_tpu.config import HDBSCANParams
    from hdbscan_tpu.models import mr_hdbscan
    from hdbscan_tpu.ops import blockscan, tiled
    from hdbscan_tpu.parallel.mesh import get_mesh
    from hdbscan_tpu.utils.datasets import make_gauss

    # Production budgets must be in force — this test exists to exercise
    # them (the dryrun leg lowers both).
    assert blockscan._BATCH_SLOT_BUDGET == 1 << 21
    assert tiled._DISPATCH_ROWS == 1 << 17

    n = 1 << 17  # 131,072 >= the verdict's 100k bar
    data, truth = make_gauss(n, dims=4, n_clusters=12, separation=9.0, seed=3)
    params = HDBSCANParams(
        min_points=6,
        min_cluster_size=n // 100,
        processing_units=8192,
        seed=0,
        k=0.02,
        boundary_quality=0.05,
    )
    mesh = get_mesh(jax.devices()[:8])
    r_mesh = mr_hdbscan.fit(data, params, mesh=mesh)
    r_ref = mr_hdbscan.fit(data, params)
    assert np.array_equal(r_mesh.labels, r_ref.labels), (
        "production-budget boundary fit diverges between mesh and unsharded"
    )
    # Sanity: the run actually exercised the windowed machinery (blocks +
    # boundary selection happened, quality sane on a separated synthetic).
    from hdbscan_tpu.utils.evaluation import adjusted_rand_index

    ari = adjusted_rand_index(r_mesh.labels, truth)
    assert ari > 0.98, f"ARI vs truth {ari:.4f} unexpectedly low"
