"""CLI driver end-to-end (the Main.main capability, argv honored)."""

import os

import numpy as np
import pytest

from hdbscan_tpu.cli import main

REFERENCE_DATASET = "/root/reference/数据集/dataset.txt"
require_reference_dataset = pytest.mark.skipif(
    not os.path.exists(REFERENCE_DATASET),
    reason=f"reference dataset not available ({REFERENCE_DATASET})",
)


class TestCLI:
    @require_reference_dataset
    def test_iris_exact_path(self, tmp_path, capsys):
        rc = main(
            [
                f"file={REFERENCE_DATASET}",
                "minPts=4",
                "minClSize=4",
                "processing_units=200",
                f"out_dir={tmp_path}",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "exact" in out and "2 clusters" in out
        for kind in ("hierarchy", "tree", "partition", "outlier_scores", "visualization"):
            files = [f for f in os.listdir(tmp_path) if kind.split("_")[0] in f]
            assert files, f"missing {kind} output"
        # partition file round-trips to the expected labels
        part = np.loadtxt(tmp_path / "dataset_partition.csv", delimiter=",")
        assert part.shape == (150,)
        assert set(np.unique(part)) == {2.0, 3.0}

    @require_reference_dataset
    def test_mr_path_with_flags(self, tmp_path, capsys):
        rc = main(
            [
                f"file={REFERENCE_DATASET}",
                "minPts=4",
                "minClSize=4",
                "processing_units=60",
                "k=0.2",
                "variant=rs",
                "dedup=true",
                "seed=1",
                f"out_dir={tmp_path}",
            ]
        )
        assert rc == 0
        assert "mr (" in capsys.readouterr().out

    def test_bad_flag_errors(self, capsys):
        assert main(["file=x", "bogus=1"]) == 2
        assert "unknown flag" in capsys.readouterr().err

    def test_help(self, capsys):
        assert main(["--help"]) == 0
        assert "minPts" in capsys.readouterr().out


def _two_blob_csv(tmp_path, n=8192):
    """n=8192, d=2 is the smallest geometry where the DEFAULT ring tiles
    give a padded shard of exactly n/8 rows per device on the forced
    8-device mesh — the shape the README's HBM-ceiling math assumes and
    the one the replication gate certifies with ~0.6x headroom."""
    rng = np.random.default_rng(7)
    pts = np.concatenate(
        [
            rng.normal(0.0, 1.0, (n // 2, 2)),
            rng.normal(8.0, 1.0, (n - n // 2, 2)),
        ]
    )
    rng.shuffle(pts)
    path = tmp_path / "blobs.csv"
    np.savetxt(path, pts, delimiter=",")
    return path


class TestShardedFitCLI:
    """``fit_sharding=sharded`` + ``--assert-not-replicated``: the ISSUE
    acceptance pair at the CLI surface (README "One sharded program")."""

    def test_sharded_fit_green_under_gate(self, tmp_path, capsys):
        trace = tmp_path / "trace.jsonl"
        rc = main(
            [
                f"file={_two_blob_csv(tmp_path)}",
                "minPts=5",
                "minClSize=10",
                "fit_sharding=sharded",
                # Sharded routing honors processing_units now (above it the
                # MR pipeline runs with sharded scanners); pin the exact
                # one-program leg the gate certifies.
                "processing_units=16384",
                "--assert-not-replicated",
                "--trace-out",
                str(trace),
                f"out_dir={tmp_path}",
            ]
        )
        assert rc == 0
        assert "exact-sharded" in capsys.readouterr().out
        # The emitted trace must satisfy the sharded-fit event schemas
        # (scripts/check_trace.py): ring rounds contiguous, Borůvka
        # components strictly contracting, gate event ok=True.
        from scripts import check_trace

        events, errors = check_trace.validate_trace(str(trace))
        assert not errors, errors
        stages = {e.get("stage") for e in events}
        # Exact sharded fit = ring k-NN scan for cores + sharded Borůvka
        # for the MST, certified by the gate event (the rp-forest stages
        # only appear under knn_index=rpforest).
        assert {
            "ring_knn_scan",
            "shard_boruvka_scan",
            "replication_gate",
        } <= stages

    def test_sharded_device_fit_one_sync(self, tmp_path, capsys):
        """The in-jit contraction leg: ``mst_backend=device`` runs every
        Borůvka round in ONE while_loop dispatch, so the whole fit crosses
        the host boundary exactly once — and must still be green under the
        replication gate (the input panel is donated, the edge buffers
        leave the program row-sharded)."""
        trace = tmp_path / "trace.jsonl"
        rc = main(
            [
                f"file={_two_blob_csv(tmp_path)}",
                "minPts=5",
                "minClSize=10",
                "fit_sharding=sharded",
                "mst_backend=device",
                "processing_units=16384",
                "--assert-not-replicated",
                "--trace-out",
                str(trace),
                f"out_dir={tmp_path}",
            ]
        )
        assert rc == 0
        assert "exact-sharded" in capsys.readouterr().out
        from scripts import check_trace

        events, errors = check_trace.validate_trace(str(trace))
        assert not errors, errors
        syncs = [e for e in events if e.get("stage") == "host_sync"]
        assert len(syncs) == 1, f"one host sync per sharded fit, got {syncs}"
        rounds = [e for e in events if e.get("stage") == "mst_round"]
        assert rounds and all(e.get("sharded") is True for e in rounds)
        comps = [e["components"] for e in rounds]
        assert comps == sorted(comps, reverse=True) and len(set(comps)) == len(
            comps
        ), f"components must strictly contract: {comps}"
        gate = [e for e in events if e.get("stage") == "replication_gate"]
        assert gate and gate[0].get("ok") is True

    @pytest.mark.slow
    def test_replicated_fit_trips_gate(self, tmp_path, capsys):
        """The negative control: the replicated program materializes O(n)
        buffers whole on every device, and the SAME gate must refuse it
        with the documented exit code 3."""
        rc = main(
            [
                f"file={_two_blob_csv(tmp_path)}",
                "minPts=5",
                "minClSize=10",
                "fit_sharding=replicated",
                "--assert-not-replicated",
                f"out_dir={tmp_path}",
            ]
        )
        assert rc == 3
        assert "replicated device buffer" in capsys.readouterr().err
