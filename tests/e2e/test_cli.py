"""CLI driver end-to-end (the Main.main capability, argv honored)."""

import os

import numpy as np
import pytest

from hdbscan_tpu.cli import main

REFERENCE_DATASET = "/root/reference/数据集/dataset.txt"
require_reference_dataset = pytest.mark.skipif(
    not os.path.exists(REFERENCE_DATASET),
    reason=f"reference dataset not available ({REFERENCE_DATASET})",
)


class TestCLI:
    @require_reference_dataset
    def test_iris_exact_path(self, tmp_path, capsys):
        rc = main(
            [
                f"file={REFERENCE_DATASET}",
                "minPts=4",
                "minClSize=4",
                "processing_units=200",
                f"out_dir={tmp_path}",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "exact" in out and "2 clusters" in out
        for kind in ("hierarchy", "tree", "partition", "outlier_scores", "visualization"):
            files = [f for f in os.listdir(tmp_path) if kind.split("_")[0] in f]
            assert files, f"missing {kind} output"
        # partition file round-trips to the expected labels
        part = np.loadtxt(tmp_path / "dataset_partition.csv", delimiter=",")
        assert part.shape == (150,)
        assert set(np.unique(part)) == {2.0, 3.0}

    @require_reference_dataset
    def test_mr_path_with_flags(self, tmp_path, capsys):
        rc = main(
            [
                f"file={REFERENCE_DATASET}",
                "minPts=4",
                "minClSize=4",
                "processing_units=60",
                "k=0.2",
                "variant=rs",
                "dedup=true",
                "seed=1",
                f"out_dir={tmp_path}",
            ]
        )
        assert rc == 0
        assert "mr (" in capsys.readouterr().out

    def test_bad_flag_errors(self, capsys):
        assert main(["file=x", "bogus=1"]) == 2
        assert "unknown flag" in capsys.readouterr().err

    def test_help(self, capsys):
        assert main(["--help"]) == 0
        assert "minPts" in capsys.readouterr().out
