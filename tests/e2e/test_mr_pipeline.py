"""End-to-end MR-HDBSCAN* pipeline tests: recursive sampling + bubbles vs the
exact single-block result (the reference's validation protocol, SURVEY.md §4)."""

import numpy as np
import pytest

from hdbscan_tpu.config import HDBSCANParams
from hdbscan_tpu.models import hdbscan, mr_hdbscan
from hdbscan_tpu.parallel.mesh import get_mesh
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from tests.conftest import make_blobs


class TestSmallDatasetExactPath:
    def test_single_level_matches_exact(self, iris):
        """Dataset fits processing_units: the MR pipeline IS the exact path."""
        params = HDBSCANParams(min_points=4, min_cluster_size=4, processing_units=200)
        exact = hdbscan.fit(iris, params)
        mr = mr_hdbscan.fit(iris, params)
        assert mr.n_levels == 1
        assert adjusted_rand_index(mr.labels, exact.labels) == 1.0

    def test_iris_recursive(self, iris):
        """Force recursion on Iris (capacity 50 < 150) and compare to exact."""
        params = HDBSCANParams(
            min_points=4, min_cluster_size=4, processing_units=50, k=0.2, seed=1
        )
        exact = hdbscan.fit(iris, params.replace(processing_units=200))
        mr = mr_hdbscan.fit(iris, params)
        assert mr.n_levels >= 2
        assert np.all(mr.labels >= 0)
        ari = adjusted_rand_index(mr.labels, exact.labels)
        assert ari > 0.55, f"ARI vs exact too low: {ari}"


class TestBlobsRecursive:
    def test_blobs_high_ari(self, rng):
        pts, truth = make_blobs(rng, n=1200, d=3, centers=4, spread=0.08)
        params = HDBSCANParams(
            min_points=5, min_cluster_size=10, processing_units=200, k=0.15, seed=0
        )
        mr = mr_hdbscan.fit(pts, params)
        assert mr.n_levels >= 2
        ari = adjusted_rand_index(mr.labels, truth, noise_as_singletons=False)
        assert ari > 0.9, f"ARI vs ground truth too low: {ari}"

    def test_deterministic_given_seed(self, rng):
        pts, _ = make_blobs(rng, n=400, d=2, centers=3)
        params = HDBSCANParams(min_points=4, min_cluster_size=5, processing_units=100, seed=7)
        a = mr_hdbscan.fit(pts, params)
        b = mr_hdbscan.fit(pts, params)
        np.testing.assert_array_equal(a.labels, b.labels)

    def test_all_points_labeled_and_core_finite(self, rng):
        pts, _ = make_blobs(rng, n=600, d=3, centers=3)
        params = HDBSCANParams(min_points=4, min_cluster_size=8, processing_units=150)
        mr = mr_hdbscan.fit(pts, params)
        assert np.all(np.isfinite(mr.core_distances))
        assert len(mr.labels) == len(pts)

    def test_runs_on_mesh(self, rng):
        pts, truth = make_blobs(rng, n=800, d=3, centers=4, spread=0.08)
        params = HDBSCANParams(min_points=4, min_cluster_size=8, processing_units=120, seed=3)
        mr = mr_hdbscan.fit(pts, params, mesh=get_mesh())
        ari = adjusted_rand_index(mr.labels, truth, noise_as_singletons=False)
        assert ari > 0.85


class TestLevelTrace:
    def test_level_stats_recorded(self, rng):
        pts, _ = make_blobs(rng, n=500, d=2, centers=2)
        params = HDBSCANParams(min_points=4, min_cluster_size=5, processing_units=100)
        mr = mr_hdbscan.fit(pts, params)
        assert len(mr.levels) == mr.n_levels
        assert mr.levels[0].n_active == 500
        assert sum(l.n_processed for l in mr.levels) == 500


class TestRSVariant:
    def test_rs_blobs_high_ari(self, rng):
        pts, truth = make_blobs(rng, n=1200, d=3, centers=4, spread=0.08)
        params = HDBSCANParams(
            min_points=5,
            min_cluster_size=10,
            processing_units=200,
            k=0.15,
            seed=0,
            variant="rs",
        )
        mr = mr_hdbscan.fit(pts, params)
        assert mr.n_levels >= 2
        ari = adjusted_rand_index(mr.labels, truth, noise_as_singletons=False)
        assert ari > 0.9, f"RS ARI vs ground truth too low: {ari}"

    def test_rs_differs_from_db_but_both_converge(self, rng):
        pts, _ = make_blobs(rng, n=900, d=2, centers=3, spread=0.1)
        base = HDBSCANParams(min_points=4, min_cluster_size=8, processing_units=150, seed=2)
        db = mr_hdbscan.fit(pts, base)
        rs = mr_hdbscan.fit(pts, base.replace(variant="rs"))
        assert len(db.labels) == len(rs.labels) == 900
        # both variants should broadly agree on strong blob structure
        assert adjusted_rand_index(db.labels, rs.labels) > 0.5

    def test_rs_single_gaussian_terminates(self, rng):
        """Forced-split guard must also work for the RS variant."""
        pts = rng.normal(size=(700, 2))
        params = HDBSCANParams(
            min_points=4, min_cluster_size=10, processing_units=100, k=0.1, variant="rs"
        )
        mr = mr_hdbscan.fit(pts, params)
        assert len(mr.labels) == 700

    def test_variant_flag_validation(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            HDBSCANParams(variant="nope")


class TestForcedSplit:
    def test_single_gaussian_terminates_and_tracks_exact(self, rng):
        """One dense blob: bubble model finds a single cluster every level —
        the forced-split guard must terminate the recursion, and the per-level
        glue harvest (exact_inter_edges) must keep the distributed tree close
        to the exact tree (which itself fragments a gaussian into micro-
        clusters at minClSize=10 — the old sample-distance-only glue
        artificially merged forced-split chunks instead)."""
        pts = rng.normal(size=(700, 2))
        params = HDBSCANParams(min_points=4, min_cluster_size=10, processing_units=100, k=0.1)
        mr = mr_hdbscan.fit(pts, params)
        assert len(mr.labels) == 700
        exact = hdbscan.fit(pts, params.replace(processing_units=1000))
        ari = adjusted_rand_index(mr.labels, exact.labels)
        assert ari > 0.2, f"ARI vs exact too low: {ari}"


class TestAlternateMetrics:
    def test_mr_pipeline_with_manhattan_metric(self, iris):
        """Alternate distance plug-ins must flow through the WHOLE distributed
        pipeline (blocks, bubbles, glue, refinement), not just the exact path."""
        params = HDBSCANParams(
            min_points=4,
            min_cluster_size=4,
            processing_units=50,
            k=0.2,
            seed=0,
            dist_function="manhattan",
        )
        exact_res = hdbscan.fit(iris, params)
        mr = mr_hdbscan.fit(iris, params)
        assert mr.n_levels >= 2
        ari = adjusted_rand_index(mr.labels, exact_res.labels)
        assert ari > 0.5, f"manhattan MR vs exact ARI too low: {ari}"


class TestShardedMRPipeline:
    """``fit_sharding=sharded`` on the MR pipeline: the glue harvests, the
    boundary rescan, and (under dedup) the weighted global-core scan all
    run through the row-sharded scanners instead of silently forcing the
    replicated program (ISSUE 18 acceptance: ARI >= 0.99x the replicated
    MR fit on the 5k dataset)."""

    def test_sharded_ari_tracks_replicated_on_5k(self, rng):
        pts, truth = make_blobs(rng, n=5000, d=3, centers=4, spread=0.08)
        params = HDBSCANParams(
            min_points=5, min_cluster_size=10, processing_units=1024,
            k=0.15, seed=0,
        )
        mesh = get_mesh()
        events = []
        rep = mr_hdbscan.fit(pts, params, mesh=mesh)
        shd = mr_hdbscan.fit(
            pts, params.replace(fit_sharding="sharded"), mesh=mesh,
            trace=lambda s, **kw: events.append(s),
        )
        # The sharded scanners actually ran: the glue harvest goes through
        # the row-sharded Boruvka scanner, not the replicated tiles.
        assert "shard_boruvka_scan" in events
        ari_rep = adjusted_rand_index(rep.labels, truth, noise_as_singletons=False)
        ari_shd = adjusted_rand_index(shd.labels, truth, noise_as_singletons=False)
        assert ari_rep > 0.9, f"replicated baseline degenerate: {ari_rep}"
        assert ari_shd >= 0.99 * ari_rep, (
            f"sharded MR fit lost quality: {ari_shd} < 0.99 * {ari_rep}"
        )
