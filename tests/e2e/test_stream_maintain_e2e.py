"""End-to-end acceptance for incremental hierarchy maintenance
(README "Incremental maintenance").

Three legs:

- online absorption through the real HTTP server: a drifting stream is
  absorbed by the maintainer (``stream_maintain=incremental``) behind
  blue/green handle refreshes — generation advances with
  ``model_swap reason="maintain"``, a predict at the novel mass attaches
  non-noise, ZERO re-fits run even though the buffered rows blow through
  ``stream_refit_budget`` (an active maintainer suppresses the budget
  trigger), the second refresh at an unchanged capacity re-warms with
  ``jit_compiles == 0`` (no AOT re-warm), and the trace/metrics artifacts
  pass scripts/check_trace.py / scripts/check_metrics.py,
- the fallback ladder: a maintainer tripping its dirty-work contract
  demotes the stream to the circuit-gated full re-fit
  (``maintain_fallback`` trace event, re-fit with
  ``reason="maintain_fallback"``) while the server keeps serving,
- SIGKILL chaos: a WAL writer folding the maintainer alongside the buffer
  is killed mid-stream; recovery replays the buffer, re-verifies the
  snapshot's maintenance watermark digests, finishes the stream, and lands
  BITWISE on the uninterrupted run's state (maintainer ``state_dict``
  included — MST sha, edit-journal sha, every counter). The driver is
  jax-free (exhaustive candidates), which ``incremental/`` guarantees.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import hdbscan
from hdbscan_tpu.serve.server import ClusterServer
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
from scripts import check_metrics, check_trace

CENTERS = np.asarray([(0.0, 0.0, 0.0), (6.0, 6.0, 6.0), (0.0, 8.0, 0.0)])
NOVEL = np.asarray((12.0, -6.0, 5.0))
SPREAD = 0.25


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(0)
    params = HDBSCANParams(
        min_points=8, min_cluster_size=25, processing_units=1024
    )
    truth = np.arange(600) % len(CENTERS)
    train = CENTERS[truth] + rng.normal(0, SPREAD, (600, 3))
    model = hdbscan.fit(train, params).to_cluster_model(train, params)
    return model, params


def _post(base, path, obj, timeout=60):
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def test_maintain_absorbs_stream_online(fitted, tmp_path):
    model, params0 = fitted
    params = dataclasses.replace(
        params0,
        stream_maintain="incremental",
        maintain_refresh_every=16,
        stream_refit_budget=32,  # tiny: only suppression keeps refits at 0
        stream_drift_threshold=50.0,
    )
    trace_path = tmp_path / "trace.jsonl"
    tracer = Tracer(sinks=[JsonlSink(str(trace_path))])
    srv = ClusterServer(
        model, max_batch=64, port=0, tracer=tracer, ingest=True,
        params=params, model_dir=str(tmp_path / "models"),
    ).start()
    base = f"http://{srv.host}:{srv.port}"
    try:
        rng = np.random.default_rng(4)
        for _ in range(10):
            pts = NOVEL + rng.normal(0, SPREAD, (16, 3))
            out = _post(base, "/ingest", {"points": pts.tolist()})
            assert out["rows"] == 16
        health = json.loads(_get(base, "/healthz"))
        maintain = health["stream"]["maintain"]
        assert maintain["mode"] == "incremental" and maintain["active"]
        assert maintain["refreshes"] >= 2, maintain
        assert maintain["fallbacks"] == 0 and maintain["inserts"] > 16

        # Blue/green refresh advanced the served generation without a
        # single re-fit, despite buffered novel rows >> refit budget.
        assert health["generation"] >= 2
        assert srv.refitter.refits_ok == 0 == srv.refitter.refits_failed
        assert srv.buffer.stats()["buffered"] > params.stream_refit_budget

        # The novel mass now attaches as a real cluster.
        out = _post(base, "/predict", {"points": [NOVEL.tolist()]})
        assert out["labels"][0] != -1
        assert out["generation"] == health["generation"]

        # Refresh is swap-cheap: capacity was unchanged after the first
        # maintained publish, so the newest handle re-warmed on the shared
        # jit cache with zero fresh compiles.
        assert srv._handle.model.n_train > model.n_train  # padded capacity
        assert srv._handle.warmup_info["jit_compiles"] == 0

        scrape = _get(base, "/metrics")
    finally:
        srv.close()
        tracer.close()

    events, errors = check_trace.validate_trace(str(trace_path))
    assert errors == [], errors
    stages = {e["stage"] for e in events}
    assert {"mst_splice", "subtree_finalize", "model_swap"} <= stages
    assert "model_refit" not in stages
    swaps = [e for e in events if e["stage"] == "model_swap"]
    assert all(e["reason"] == "maintain" for e in swaps)

    scrape_path = tmp_path / "scrape.txt"
    scrape_path.write_text(scrape)
    parsed, merrors = check_metrics.validate_exposition(scrape, "scrape")
    assert merrors == [], merrors
    outcomes = {
        dict(labels).get("outcome")
        for (name, labels) in parsed["samples"]
        if name == "hdbscan_tpu_maintain_total"
    }
    assert {"inserted", "spliced", "refresh"} <= outcomes


def test_maintain_fallback_demotes_to_refit(fitted, tmp_path):
    """The fallback ladder: incremental -> circuit-gated re-fit -> pinned
    generation. A dirty-work contract trip drops the maintainer, emits
    ``maintain_fallback``, and kicks a re-fit with that reason."""
    model, params0 = fitted
    params = dataclasses.replace(
        params0,
        stream_maintain="incremental",
        maintain_refresh_every=1,  # splice on the first novel insert
        maintain_dirty_max_frac=1e-9,  # which then always over-trips
        stream_refit_budget=100_000,
        stream_drift_threshold=50.0,
    )
    trace_path = tmp_path / "trace.jsonl"
    tracer = Tracer(sinks=[JsonlSink(str(trace_path))])
    srv = ClusterServer(
        model, max_batch=64, tracer=tracer, ingest=True,
        params=params, model_dir=str(tmp_path / "models"),
    )
    try:
        assert srv.maintainer is not None
        rng = np.random.default_rng(5)
        pts = NOVEL + rng.normal(0, SPREAD, (8, 3))
        out = srv.ingest(pts)
        assert out["maintained"]["fallback"] is True
        assert srv.maintainer is None  # dropped, budget gate un-suppressed
        stats = srv.maintain_stats()
        assert stats["active"] is False and stats["fallbacks"] == 1
        assert "dirty fraction" in stats["last_error"]
        # The re-fit was kicked with the demotion reason and the server
        # kept serving the pinned generation meanwhile.
        assert out["refit_started"] is True
        assert srv.generation >= 1
        assert srv.refitter.join(timeout=120)
        assert srv.refitter.refits_ok == 1
    finally:
        srv.close()
        tracer.close()

    events, errors = check_trace.validate_trace(str(trace_path))
    assert errors == [], errors
    falls = [e for e in events if e["stage"] == "maintain_fallback"]
    assert len(falls) == 1 and "dirty fraction" in falls[0]["error"]
    refits = [e for e in events if e["stage"] == "model_refit"]
    assert refits and refits[0]["reason"] == "maintain_fallback"


#: Stand-alone WAL + maintainer writer for the SIGKILL leg. Exhaustive
#: candidate mode keeps it jax-free; every batch folds its novel chunks
#: through the maintainer and snapshots carry the maintenance watermark.
_KILL_CHILD = r"""
import sys, types
import numpy as np
from hdbscan_tpu.incremental import HierarchyMaintainer
from hdbscan_tpu.stream.buffer import IngestBuffer
from hdbscan_tpu.stream.drift import DriftDetector
from hdbscan_tpu.stream.wal import StreamJournal

wal_dir = sys.argv[1]
rng = np.random.default_rng(2)
base = rng.integers(0, 48, (64, 3)).astype(np.float64) / 8.0
model = types.SimpleNamespace(data=base)
buf = IngestBuffer(model, reservoir_size=16, seed=0)
drift = DriftDetector(rng.uniform(0, 1, 256), rng.integers(-1, 3, 256))
jr = StreamJournal(wal_dir, snapshot_every=4)
jr.open("maintain-digest", buf, drift)
m = HierarchyMaintainer(base, min_pts=4, refresh_every=8)
srng = np.random.default_rng(7)
for i in range(10_000):
    pts = 20.0 + srng.integers(0, 48, (4, 3)).astype(np.float64) / 8.0
    labels = srng.integers(-1, 3, 4)
    prob = srng.uniform(0, 1, 4)
    scores = srng.uniform(0, 1, 4)
    c0 = buf.novel_chunk_count
    buf.absorb(pts, labels, prob)
    drift.update(labels, scores)
    for idx in range(c0, buf.novel_chunk_count):
        m.rebuild(buf.novel_chunk(idx))
    jr.append_ingest(pts, labels, prob, scores)
    jr.maybe_snapshot(buf, drift, maintain=m.state_dict())
    print(f"ACK {i + 1}", flush=True)
"""


def test_sigkill_recovery_is_bitwise(tmp_path):
    """Kill the maintaining writer mid-stream; recovery must verify the
    persisted watermark and finish bitwise-identical to an uninterrupted
    run — buffer state AND maintainer digests."""
    import types

    from hdbscan_tpu.incremental import HierarchyMaintainer
    from hdbscan_tpu.stream.buffer import IngestBuffer
    from hdbscan_tpu.stream.drift import DriftDetector
    from hdbscan_tpu.stream.wal import StreamJournal

    total_batches = 20
    rng = np.random.default_rng(2)
    base = rng.integers(0, 48, (64, 3)).astype(np.float64) / 8.0
    drift_ref = rng.uniform(0, 1, 256), rng.integers(-1, 3, 256)

    def batches():
        srng = np.random.default_rng(7)
        for _ in range(total_batches):
            yield (
                20.0 + srng.integers(0, 48, (4, 3)).astype(np.float64) / 8.0,
                srng.integers(-1, 3, 4),
                srng.uniform(0, 1, 4),
                srng.uniform(0, 1, 4),
            )

    def fold(buf, drift, m, batch, journal=None):
        pts, labels, prob, scores = batch
        c0 = buf.novel_chunk_count
        buf.absorb(pts, labels, prob)
        drift.update(labels, scores)
        for idx in range(c0, buf.novel_chunk_count):
            m.rebuild(buf.novel_chunk(idx))
        if journal is not None:
            journal.append_ingest(pts, labels, prob, scores)
            journal.maybe_snapshot(buf, drift, maintain=m.state_dict())

    # Uninterrupted reference run (no journal).
    ref_buf = IngestBuffer(types.SimpleNamespace(data=base), reservoir_size=16, seed=0)
    ref_drift = DriftDetector(*drift_ref)
    ref_m = HierarchyMaintainer(base, min_pts=4, refresh_every=8)
    for b in batches():
        fold(ref_buf, ref_drift, ref_m, b)
    assert ref_m.inserts > 0 and ref_m.splices > 0  # the stream is novel

    # Crashed run: SIGKILL the child once it acks 7 durable batches.
    wal_dir = tmp_path / "wal"
    wal_dir.mkdir()
    repo = Path(__file__).resolve().parents[2]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILL_CHILD, str(wal_dir)],
        stdout=subprocess.PIPE, cwd=str(repo), env=env, text=True,
    )
    acked = 0
    try:
        for line in proc.stdout:
            assert line.startswith("ACK ")
            acked = int(line.split()[1])
            if acked >= 7:
                os.kill(proc.pid, signal.SIGKILL)
                break
    finally:
        proc.kill()
        proc.wait(timeout=30)
    assert acked == 7

    # Recovery: replay the WAL into fresh state machines, re-verify the
    # maintenance watermark against the rebuilt maintainer, continue.
    buf = IngestBuffer(types.SimpleNamespace(data=base), reservoir_size=16, seed=0)
    drift = DriftDetector(*drift_ref)
    jr = StreamJournal(str(wal_dir), snapshot_every=4)
    info = jr.open("maintain-digest", buf, drift)
    watermark = info["maintain"]
    assert watermark is not None and watermark["inserts"] > 0
    replayed = buf.stats()["rows_seen"] // 4
    assert acked <= replayed <= acked + 2  # fsync-before-ack

    m = HierarchyMaintainer(base, min_pts=4, refresh_every=8)
    for chunk in buf.novel_chunks():
        m.rebuild(chunk, verify_at=(watermark["inserts"], watermark))
    remaining = list(batches())[replayed:]
    for b in remaining:
        fold(buf, drift, m, b, journal=jr)
    jr.close()

    # Bitwise: buffer (reservoir RNG included) and maintainer watermark —
    # sha256 over the MST arrays and the edit journal, every counter.
    assert buf.state_dict() == ref_buf.state_dict()
    assert drift.state_dict() == ref_drift.state_dict()
    assert m.state_dict() == ref_m.state_dict()
