"""Real multi-process (multi-controller) execution of the MR pipeline.

The reference's deployment is inherently multi-worker — a Spark driver plus
executors wired by the ``clusterName`` master flag (``main/Main.java:89-95``),
with every stage boundary crossing the network. The TPU-native counterpart is
JAX multi-controller: one process per host, ``jax.distributed.initialize``
joining them into one logical device set, sharded scans splitting rows across
ALL processes' devices, and DCN allgathers (``parallel/mesh.fetch``) replacing
the shuffle read. This test runs the CLI under TWO actual OS processes with a
local coordinator (CPU backend, 2 virtual devices each = a 4-device global
mesh) and pins byte-identical outputs against a single-process run over an
identically-shaped 4-device mesh — the determinism contract that makes
multi-controller SPMD correct (every process must take the same decisions).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
OUTPUT_KINDS = ("hierarchy", "tree", "partition", "outlier_scores", "visualization")


# One copy of the spawn/cleanup rules, shared with dryrun_multichip
# (parallel/distributed.py owns them).
from hdbscan_tpu.parallel.distributed import (  # noqa: E402
    communicate_all as _communicate_all,
    free_local_port as _free_port,
    hermetic_child_env,
)


def _child_env(n_local_devices: int) -> dict:
    return hermetic_child_env(n_local_devices, repo_root=REPO)


_BACKEND_UNAVAILABLE = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def _skip_if_collectives_unavailable(procs, outs):
    """Multi-controller CPU collectives are a jaxlib build capability, not a
    code path this repo controls: some jaxlib builds reject ANY multiprocess
    computation on the CPU backend at the first device_put. When a rank died
    with that exact error the environment — not the pipeline — failed, so
    skip with the rank's own words instead of reporting a red herring."""
    for i, (p, (_so, se)) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and _BACKEND_UNAVAILABLE in (se or ""):
            pytest.skip(
                "multi-controller collectives unavailable in this jaxlib "
                f"build (rank {i} stderr: {_BACKEND_UNAVAILABLE!r})"
            )


def _run_cli(args: list[str], n_local_devices: int, timeout: int = 300):
    return subprocess.run(
        [sys.executable, "-m", "hdbscan_tpu", *args],
        env=_child_env(n_local_devices),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def _cli_args(dataset_file, out_dir, cluster_name):
    return [
        f"file={dataset_file}",
        "minPts=4",
        "minClSize=50",
        "processing_units=256",
        "k=0.1",
        "seed=3",
        f"out_dir={out_dir}",
        f"clusterName={cluster_name}",
    ]


class TestMultiProcess:
    def test_two_process_cli_matches_single_process(self, tmp_path):
        """2 OS processes x 2 devices == 1 process x 4 devices, byte-for-byte."""
        rng = np.random.default_rng(7)
        pts = np.concatenate(
            [rng.normal(c, 0.4, size=(400, 3)) for c in ((0, 0, 0), (8, 0, 0), (0, 8, 8))]
        )
        dataset = str(tmp_path / "blobs.txt")
        np.savetxt(dataset, pts, fmt="%.6f")

        # Reference run: one controller, 4 local virtual devices.
        out1 = tmp_path / "single"
        r = _run_cli(_cli_args(dataset, out1, "local"), n_local_devices=4)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "mr (" in r.stdout

        # Distributed run: two controllers x 2 devices, local coordinator.
        port = _free_port()
        out2 = tmp_path / "multi"
        env_args = lambda pid: _cli_args(  # noqa: E731
            dataset, out2, f"127.0.0.1:{port},{pid},2"
        )
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "hdbscan_tpu", *env_args(pid)],
                env=_child_env(2),
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for pid in (0, 1)
        ]
        outs = _communicate_all(procs)
        _skip_if_collectives_unavailable(procs, outs)
        for p, (so, se) in zip(procs, outs):
            assert p.returncode == 0, f"rank failed:\n{se[-2000:]}"
        # Only process 0 writes/prints (rank 1's stdout may carry Gloo
        # connection banners from the CPU collectives backend, but no
        # pipeline output).
        assert "mr (" in outs[0][0]
        assert "hdbscan-tpu" not in outs[1][0] and "mr (" not in outs[1][0]
        assert "2 processes" in outs[0][1] and "4 devices" in outs[0][1]

        files1 = sorted(os.listdir(out1))
        files2 = sorted(os.listdir(out2))
        assert files1 == files2 and len(files1) == len(OUTPUT_KINDS)
        for f in files1:
            b1 = (out1 / f).read_bytes()
            b2 = (out2 / f).read_bytes()
            assert b1 == b2, f"{f} differs between single- and multi-process"

    def test_four_process_cli_matches_single_process(self, tmp_path):
        """4 OS processes x 2 devices == 1 process x 8 devices.

        The >2-rank leg (VERDICT r3 item 8c): cross-process assembly,
        allgather fetch, and the deterministic driver loop must hold beyond
        the pairwise case — rank counts change slab boundaries, mesh shape,
        and the collective participant set.
        """
        rng = np.random.default_rng(9)
        pts = np.concatenate(
            [rng.normal(c, 0.5, size=(300, 3)) for c in ((0, 0, 0), (9, 0, 0), (0, 9, 0), (9, 9, 9))]
        )
        dataset = str(tmp_path / "blobs4.txt")
        np.savetxt(dataset, pts, fmt="%.6f")

        out1 = tmp_path / "single"
        r = _run_cli(_cli_args(dataset, out1, "local"), n_local_devices=8)
        assert r.returncode == 0, r.stderr[-2000:]

        port = _free_port()
        out2 = tmp_path / "multi"
        procs = [
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "hdbscan_tpu",
                    *_cli_args(dataset, out2, f"127.0.0.1:{port},{pid},4"),
                ],
                env=_child_env(2),
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for pid in range(4)
        ]
        outs = _communicate_all(procs)
        _skip_if_collectives_unavailable(procs, outs)
        for p, (so, se) in zip(procs, outs):
            assert p.returncode == 0, f"rank failed:\n{se[-2000:]}"
        assert "4 processes" in outs[0][1] and "8 devices" in outs[0][1]

        files1 = sorted(os.listdir(out1))
        files2 = sorted(os.listdir(out2))
        assert files1 == files2 and len(files1) == len(OUTPUT_KINDS)
        for f in files1:
            assert (out1 / f).read_bytes() == (out2 / f).read_bytes(), (
                f"{f} differs between single- and 4-process runs"
            )

    def test_library_slab_and_assembly_two_process(self, tmp_path):
        """host_row_slab + global_rows_from_local + sharded scan across 2 procs.

        The library-primitive half (parallel/distributed.py): each process
        loads only ITS row slab, slabs assemble into one globally-sharded
        array, and a mesh-sharded Borůvka scan over the global mesh matches
        the single-controller scan on the same data.
        """
        script = tmp_path / "worker.py"
        script.write_text(
            """
import sys, os
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
pid, nproc, port, out = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
from hdbscan_tpu.parallel.distributed import (
    initialize_from_cluster_name, host_row_slab, global_rows_from_local)
assert initialize_from_cluster_name(f"127.0.0.1:{port},{pid},{nproc}")
assert initialize_from_cluster_name(f"127.0.0.1:{port},{pid},{nproc}")  # idempotent
from hdbscan_tpu.parallel.mesh import get_mesh, fetch
mesh = get_mesh()
rng = np.random.default_rng(11)
full = rng.normal(size=(512, 4))
groups = np.arange(512) // 128
start, stop = host_row_slab(len(full))
slab = full[start:stop]  # this process "loads" only its slab
garr = global_rows_from_local(slab, mesh, len(full))
assert not garr.is_fully_addressable
back = fetch(garr)
assert np.array_equal(back, full)
from hdbscan_tpu.ops.tiled import boruvka_glue_edges
u, v, w = boruvka_glue_edges(full, groups, core=np.zeros(len(full)), mesh=mesh)
np.savez(out, u=u, v=v, w=w)
print("RANK_OK", pid)
"""
        )
        port = _free_port()
        outs = [tmp_path / f"rank{i}.npz" for i in range(2)]
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid), "2", str(port), str(outs[pid])],
                env=_child_env(2),
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for pid in (0, 1)
        ]
        res = _communicate_all(procs)
        _skip_if_collectives_unavailable(procs, res)
        for p, (so, se) in zip(procs, res):
            assert p.returncode == 0, se[-2000:]
            assert "RANK_OK" in so

        # Both ranks harvested identical glue edges...
        a, b = np.load(outs[0]), np.load(outs[1])
        for k in ("u", "v", "w"):
            assert np.array_equal(a[k], b[k])
        # ...matching the single-controller mesh-free scan on the same data.
        from hdbscan_tpu.ops.tiled import boruvka_glue_edges

        rng = np.random.default_rng(11)
        full = rng.normal(size=(512, 4))
        groups = np.arange(512) // 128
        u, v, w = boruvka_glue_edges(full, groups, core=np.zeros(len(full)))
        assert np.array_equal(np.sort(a["w"]), np.sort(w))
