"""Chaos end-to-end acceptance for the fault-tolerance layer.

Four legs, all driven through the real HTTP server:

- fault classes under concurrent load (slow_request / predict_dispatch /
  http_reset): zero hung or malformed responses, and every injected fault
  is accounted identically by the plan, the trace, and /metrics,
- bounded-queue load shedding: 429/503 + Retry-After, shed/served/failed
  reconciles bitwise between the load generator, the trace, the batcher
  stats and ``requests_shed_total``; per-request deadlines 504 fail-fast,
- refit circuit breaker: three injected refit failures trip it open
  (visible in /healthz, /metrics and the circuit_state trace), the server
  keeps serving the pinned generation, and after ``circuit_reset_s`` a
  half-open trial refit recovers to generation 2,
- crash-safe durability: a SIGKILLed writer process loses nothing it
  acked, and a server recovered from the WAL mid-stream finishes with a
  refit pool bitwise identical to an uninterrupted run (so recovery ARI
  == uninterrupted ARI >= the 0.99x acceptance bar).

Every leg's trace passes scripts/check_trace.py and every scrape passes
scripts/check_metrics.py.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from benchmarks.loadgen import http_predict_submitter, run_load
from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.fault import inject
from hdbscan_tpu.models import hdbscan, mr_hdbscan
from hdbscan_tpu.serve.server import ClusterServer
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
from scripts import check_metrics, check_trace

#: Three fit-time blobs plus one the streaming legs drift onto.
CENTERS = np.asarray([(0.0, 0.0, 0.0), (6.0, 6.0, 6.0), (0.0, 8.0, 0.0)])
NOVEL = np.asarray((10.0, -6.0, 5.0))
SPREAD = 0.25


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Fault plans are process-global: never leak one across tests."""
    inject.clear()
    yield
    inject.clear()


@pytest.fixture(scope="module")
def fitted():
    """One small fitted model shared by every chaos leg."""
    rng = np.random.default_rng(0)
    params = HDBSCANParams(
        min_points=8, min_cluster_size=25, processing_units=1024
    )
    train, _ = _blobs(rng, 600, CENTERS)
    model = hdbscan.fit(train, params).to_cluster_model(train, params)
    return model, params, train


def _blobs(rng, n, centers):
    centers = np.atleast_2d(np.asarray(centers, float))
    truth = np.arange(n) % len(centers)
    return centers[truth] + rng.normal(0, SPREAD, (n, 3)), truth


def _post(base, path, obj, headers=None, timeout=60):
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def _stage_counts(events, stage, key):
    out = {}
    for e in events:
        if e["stage"] == stage:
            out[e[key]] = out.get(e[key], 0) + 1
    return out


def _metric(samples, name, /, **labels):
    key = (name, tuple(sorted(labels.items())))
    return samples.get(key, 0.0)


def test_faults_accounted_under_load(fitted, tmp_path):
    """Fault classes under concurrent /predict load: no hangs, no malformed
    responses, and plan == trace == /metrics fault accounting."""
    model, _, _ = fitted
    trace = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace, static={"process": 0})])
    # Installed BEFORE the server so its ctor attaches the metrics hook.
    plan = inject.install(
        "slow_request:p=0.25,seed=11,delay_s=0.01"
        ";predict_dispatch:p=0.12,seed=12"
        ";http_reset:p=0.08,seed=13",
        tracer=tracer,
    )
    srv = ClusterServer(model, max_batch=32, port=0, tracer=tracer).start()
    base = f"http://{srv.host}:{srv.port}"
    rng = np.random.default_rng(5)

    try:
        res = run_load(
            http_predict_submitter(
                base, lambda k: _blobs(rng, k, CENTERS)[0], timeout=30
            ),
            mode="closed", concurrency=4,
            batch_mix=((1, 0.5), (8, 0.3), (24, 0.2)),
            requests=80, expect_shedding=True, seed=3,
        )
        scrape = _get(base, "/metrics")
    finally:
        srv.close()
        tracer.close()

    # Every offered request terminated (the 30s client timeout would have
    # surfaced a hang as an error; run_load returning at all rules out a
    # wedged worker) and unbounded queue => nothing shed.
    assert res.offered == 80 and res.shed == 0
    assert res.errors > 0  # the reset/dispatch faults really bit

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    spans = [e for e in events if e["stage"] == "request_span"]
    assert len(spans) == 80  # exactly one span per offered request
    by_status = _stage_counts(spans, "request_span", "status")
    assert by_status.get(200, 0) == res.requests
    assert sum(v for s, v in by_status.items() if s != 200) == res.errors
    assert by_status.get(499, 0) == plan.fired()["http_reset"]

    # plan == trace == metrics, per site.
    fired = {k: v for k, v in plan.fired().items() if v}
    assert fired and fired.get("slow_request", 0) > 0
    assert _stage_counts(events, "fault_injected", "site") == fired
    parsed, merrors = check_metrics.validate_exposition(scrape, "chaos")
    assert merrors == [], merrors
    for site, n in fired.items():
        assert _metric(
            parsed["samples"], "hdbscan_tpu_faults_injected_total", site=site
        ) == n
    # requests_total double-entry: every span's status is counted.
    for status, n in by_status.items():
        assert _metric(
            parsed["samples"], "hdbscan_tpu_requests_total",
            route="/predict", status=str(status),
        ) == n


def test_bounded_queue_sheds_and_deadlines_fail_fast(fitted, tmp_path):
    """Load shedding + deadlines: 429/503 + Retry-After under overload,
    504 on an expired deadline, and shed+served+failed == offered across
    the generator, the trace, the batcher and /metrics."""
    model, _, _ = fitted
    trace = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace, static={"process": 0})])
    srv = ClusterServer(
        model, max_batch=2, port=0, tracer=tracer, queue_bound=2
    ).start()
    base = f"http://{srv.host}:{srv.port}"
    # Slow the device path so the tiny queue genuinely backs up.
    pred = srv._handle.predictor
    orig_predict = pred.predict
    pred.predict = lambda X: (time.sleep(0.03), orig_predict(X))[1]
    rng = np.random.default_rng(6)

    try:
        res = run_load(
            http_predict_submitter(
                base, lambda k: _blobs(rng, k, CENTERS)[0], timeout=30
            ),
            mode="closed", concurrency=8, batch_mix=((1, 1.0),),
            requests=120, expect_shedding=True, seed=4,
        )

        # A simultaneous burst to capture one rejection's headers.
        outcomes = []

        def probe():
            try:
                _post(base, "/predict", {"points": [[0.1, 0.1, 0.1]]})
                outcomes.append(("ok", None))
            except urllib.error.HTTPError as e:
                outcomes.append((e.code, dict(e.headers)))

        burst = [threading.Thread(target=probe) for _ in range(12)]
        for t in burst:
            t.start()
        for t in burst:
            t.join(timeout=30)
        rejected = [(c, h) for c, h in outcomes if c in (429, 503)]
        assert rejected, outcomes
        for code, headers in rejected:
            assert float(headers["Retry-After"]) > 0
            assert headers["X-Request-Id"]

        # Deadline semantics: an already-expired deadline is a 504 before
        # any batch slot is spent; a malformed header is a 400.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, "/predict", {"points": [[0.0, 0.0, 0.0]]},
                  headers={"X-Deadline-Ms": "0.001"})
        assert ei.value.code == 504
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, "/predict", {"points": [[0.0, 0.0, 0.0]]},
                  headers={"X-Deadline-Ms": "bogus"})
        assert ei.value.code == 400

        scrape = _get(base, "/metrics")
        batcher_shed = srv._handle.batcher.stats["shed"]
    finally:
        srv.close()
        tracer.close()

    assert res.shed > 0 and res.errors == 0
    assert res.requests + res.shed == 120

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    sheds = [e for e in events if e["stage"] == "request_shed"]
    manual_shed = len(rejected)
    manual_ok = sum(1 for c, _ in outcomes if c == "ok")
    assert len(sheds) == res.shed + manual_shed == batcher_shed
    assert {e["status"] for e in sheds} <= {429, 503}
    assert {e["reason"] for e in sheds} == {"queue_full"}
    spans = [e for e in events if e["stage"] == "request_span"]
    # offered = loadgen 120 + burst 12 + the two deadline probes; each
    # terminated as exactly one of span / shed.
    assert len(spans) + len(sheds) == 120 + 12 + 2
    by_status = _stage_counts(spans, "request_span", "status")
    assert by_status[200] == res.requests + manual_ok
    assert by_status[504] == 1 and by_status[400] == 1

    parsed, merrors = check_metrics.validate_exposition(scrape, "chaos")
    assert merrors == [], merrors
    assert _metric(
        parsed["samples"], "hdbscan_tpu_requests_shed_total",
        route="/predict", reason="queue_full",
    ) == len(sheds)
    assert _metric(
        parsed["samples"], "hdbscan_tpu_requests_total",
        route="/predict", status="503",
    ) == len(sheds)


def test_refit_circuit_opens_then_recovers(fitted, tmp_path):
    """Three injected refit failures trip the circuit open (healthz,
    metrics, trace agree); the server keeps serving generation 1; after
    circuit_reset_s a half-open trial refit succeeds, swaps to generation
    2 and closes the circuit."""
    model, params0, _ = fitted
    params = dataclasses.replace(
        params0,
        stream_refit_budget=150,
        stream_drift_threshold=50.0,  # budget, not drift, triggers
        circuit_failures=3,
        circuit_reset_s=0.5,
    )
    trace = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace, static={"process": 0})])
    plan = inject.install("refit_fit:count=3", tracer=tracer)
    srv = ClusterServer(
        model, max_batch=32, port=0, tracer=tracer,
        ingest=True, params=params, model_dir=str(tmp_path / "models"),
    ).start()
    # The server builds its Refitter with the production backoff; drop it
    # so the three failures happen inside the test budget.
    srv.refitter.backoff_base_s = 0.01
    srv.refitter.backoff_cap_s = 0.05
    base = f"http://{srv.host}:{srv.port}"
    rng = np.random.default_rng(9)

    try:
        open_health = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            pts, _ = _blobs(rng, 80, NOVEL)
            out = _post(base, "/ingest", {"points": pts.tolist()})
            assert out["rows"] == 80
            if open_health is None and srv._refit_circuit.state == "open":
                open_health = json.loads(_get(base, "/healthz"))
                # Degraded but serving: the pinned generation answers.
                got = _post(base, "/predict", {"points": pts[:4].tolist()})
                assert got["generation"] == 1
            if srv.generation >= 2:
                break
            time.sleep(0.05)

        assert open_health is not None, "circuit never opened"
        stream = open_health["stream"]
        assert stream["circuit"]["state"] == "open"
        assert stream["refits_failed"] == 3
        assert "InjectedFault" in stream["refit_last_error"]
        assert stream["refit_last_error_at"]

        assert srv.generation == 2, f"no recovery: health={srv.health()}"
        assert srv._refit_circuit.state == "closed"
        scrape = _get(base, "/metrics")
        health = json.loads(_get(base, "/healthz"))
        assert health["stream"]["circuit"]["state"] == "closed"
        assert health["stream"]["circuit"]["trips"] == 1
        assert health["stream"]["refits_ok"] >= 1
    finally:
        srv.close()
        tracer.close()

    assert plan.fired()["refit_fit"] == 3

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    states = [e["state"] for e in events if e["stage"] == "circuit_state"]
    assert states == ["open", "half_open", "closed"]
    refits = [e for e in events if e["stage"] == "model_refit"]
    assert sum(1 for e in refits if not e["ok"]) == 3
    assert any(e["ok"] for e in refits)
    swaps = [e for e in events if e["stage"] == "model_swap"]
    assert [e["generation"] for e in swaps] == [2]

    parsed, merrors = check_metrics.validate_exposition(scrape, "chaos")
    assert merrors == [], merrors
    samples = parsed["samples"]
    assert _metric(samples, "hdbscan_tpu_refit_failures_total") == 3
    assert _metric(
        samples, "hdbscan_tpu_faults_injected_total", site="refit_fit"
    ) == 3
    assert _metric(samples, "hdbscan_tpu_circuit_state", name="refit") == 0.0


#: Stand-alone WAL writer for the SIGKILL leg: acks each durable append on
#: stdout, so the parent can kill it mid-stream and check nothing acked is
#: lost. numpy-only — the buffer/drift/journal state machines need no jax.
_KILL_CHILD = r"""
import sys, types
import numpy as np
from hdbscan_tpu.stream.buffer import IngestBuffer
from hdbscan_tpu.stream.drift import DriftDetector
from hdbscan_tpu.stream.wal import StreamJournal

wal_dir = sys.argv[1]
rng = np.random.default_rng(0)
model = types.SimpleNamespace(data=rng.normal(0, 1, (64, 3)))
buf = IngestBuffer(model, reservoir_size=32, seed=0)
drift = DriftDetector(rng.uniform(0, 1, 512), rng.integers(-1, 3, 512))
jr = StreamJournal(wal_dir, snapshot_every=5)
jr.open("kill-digest", buf, drift)
for i in range(100_000):
    pts = rng.normal(0, 2, (4, 3))
    labels = rng.integers(-1, 3, 4)
    prob = rng.uniform(0, 1, 4)
    scores = rng.uniform(0, 1, 4)
    buf.absorb(pts, labels, prob)
    drift.update(labels, scores)
    jr.append_ingest(pts, labels, prob, scores)
    jr.maybe_snapshot(buf, drift)
    print(f"ACK {i + 1}", flush=True)
"""


@pytest.mark.slow
def test_sigkill_loses_nothing_acked(tmp_path):
    """SIGKILL a live WAL writer mid-stream: recovery replays every append
    the child acked (fsync-before-ack), at most one unacked extra."""
    import types

    from hdbscan_tpu.stream.buffer import IngestBuffer
    from hdbscan_tpu.stream.drift import DriftDetector
    from hdbscan_tpu.stream.wal import StreamJournal

    wal_dir = tmp_path / "wal"
    wal_dir.mkdir()
    repo = Path(__file__).resolve().parents[2]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILL_CHILD, str(wal_dir)],
        stdout=subprocess.PIPE, cwd=str(repo), env=env, text=True,
    )
    acked = 0
    try:
        for line in proc.stdout:
            assert line.startswith("ACK ")
            acked = int(line.split()[1])
            if acked >= 6:
                os.kill(proc.pid, signal.SIGKILL)
                break
    finally:
        proc.kill()
        proc.wait(timeout=30)
    assert acked == 6

    rng = np.random.default_rng(0)
    model = types.SimpleNamespace(data=rng.normal(0, 1, (64, 3)))
    buf = IngestBuffer(model, reservoir_size=32, seed=0)
    drift = DriftDetector(rng.uniform(0, 1, 512), rng.integers(-1, 3, 512))
    jr = StreamJournal(str(wal_dir), snapshot_every=5)
    jr.open("kill-digest", buf, drift)
    recovered = jr.stats()["seq"] - 1  # minus the begin record
    # Everything acked is durable; the kill can at most leave one extra
    # append that completed after the last ack we read.
    assert acked <= recovered <= acked + 2
    assert buf.stats()["rows_seen"] == recovered * 4
    jr.close()


@pytest.mark.slow
def test_crash_recovery_matches_uninterrupted_stream(fitted, tmp_path):
    """Server crash-sim mid-stream: a WAL-recovered server finishes the
    stream with a refit pool bitwise identical to an uninterrupted run, so
    recovery ARI == uninterrupted ARI (>= the 0.99x acceptance bar)."""
    model, params0, _ = fitted
    params = dataclasses.replace(
        params0,
        stream_refit_budget=100_000,  # no refit: this leg is about state
        stream_drift_threshold=50.0,
        stream_snapshot_every=8,
    )
    rng = np.random.default_rng(21)
    all_centers = np.vstack([CENTERS, NOVEL[None]])
    chunks = [_blobs(rng, 100, all_centers)[0] for _ in range(20)]

    def serve(wal_dir):
        return ClusterServer(
            model, max_batch=64, port=0, ingest=True, params=params,
            model_dir=str(tmp_path / "models"), wal_dir=str(wal_dir),
        )

    def stream(srv, some_chunks):
        base = f"http://{srv.host}:{srv.port}"
        for c in some_chunks:
            out = _post(base, "/ingest", {"points": c.tolist()})
            assert out["rows"] == 100

    # Uninterrupted reference run.
    srv_a = serve(tmp_path / "wal_a").start()
    stream(srv_a, chunks)
    pool_a = srv_a.buffer.refit_points(originals=200, seed=3)
    state_a = srv_a.buffer.state_dict()
    srv_a.close()

    # Crashed run: 10 chunks, then the process "dies" — nothing is closed
    # or flushed beyond what each fsync'd append already made durable; we
    # only release the port so the recovery server can exist alongside.
    srv_b = serve(tmp_path / "wal_b").start()
    stream(srv_b, chunks[:10])
    srv_b._httpd.shutdown()
    srv_b._httpd.server_close()

    # Recovery: a fresh server on the same WAL replays to the crash point
    # before serving, then finishes the stream.
    srv_c = serve(tmp_path / "wal_b")
    assert srv_c.buffer.stats()["rows_seen"] == 1000  # replayed pre-start
    rec = srv_c.journal.last_recover
    assert rec["records"] >= 1 or rec["snapshot"]
    srv_c.start()
    stream(srv_c, chunks[10:])
    pool_c = srv_c.buffer.refit_points(originals=200, seed=3)
    state_c = srv_c.buffer.state_dict()
    srv_c.close()

    assert state_c == state_a  # bitwise, RNG state included
    np.testing.assert_array_equal(pool_c, pool_a)

    # The acceptance bar, stated as ARI: fit both pools and score against
    # nearest-center truth. Bitwise-equal pools make this exact equality.
    def ari(pool):
        labels = mr_hdbscan.fit(pool, params0).labels
        d2 = ((pool[:, None, :] - all_centers[None, :, :]) ** 2).sum(-1)
        truth = np.argmin(d2, axis=1)
        return adjusted_rand_index(
            np.asarray(labels), truth, noise_as_singletons=True
        )

    ari_uninterrupted = ari(pool_a)
    ari_recovered = ari(pool_c)
    assert ari_recovered >= 0.99 * ari_uninterrupted
    assert ari_recovered > 0.3  # the pools genuinely cluster
