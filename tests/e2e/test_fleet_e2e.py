"""End-to-end fleet acceptance (README "Fleet"): real replica
subprocesses behind the asyncio router.

Three legs:

- **Multi-tenant**: 8 tenants over a 2-replica consistent-hash fleet with
  a per-replica LRU (2) far under the tenant count — every tenant's
  response bitwise-matches a dedicated single-model server over the same
  artifact, evictions show up in the aggregated /metrics, a hammered
  tenant sheds 429 with a Retry-After hint, and the router trace passes
  ``scripts/check_trace.py``.
- **Re-warm economics** (in-process): evicting and re-warming tenants
  whose shapes were seen before costs zero jit compiles — the
  steady-state-no-recompile property survives multi-tenancy.
- **Chaos**: SIGKILL a replica mid-load — requests re-route in place
  (faster than one health interval), the replica respawns and replays its
  WAL with zero acked-ingest loss, no request hangs, and
  served+shed+failed reconciles with offered.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.fleet import FleetRouter, TenantRegistry
from hdbscan_tpu.models import hdbscan
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
from scripts import check_metrics, check_trace

CENTERS = np.asarray([(0.0, 0.0, 0.0), (6.0, 6.0, 6.0), (0.0, 8.0, 0.0)])


@pytest.fixture(scope="module")
def fleet_model(tmp_path_factory):
    """One small fitted artifact shared by every leg (the tenants are
    copies of it, so tenant warmups ride the process jit cache)."""
    rng = np.random.default_rng(11)
    pts = CENTERS[np.arange(360) % 3] + rng.normal(0, 0.25, (360, 3))
    params = HDBSCANParams(
        min_points=5, min_cluster_size=25, processing_units=512,
    )
    model = hdbscan.fit(pts, params).to_cluster_model(pts, params)
    path = str(tmp_path_factory.mktemp("fleet-model") / "model.npz")
    model.save(path)
    return path, pts


def _post(base, path, obj, timeout=120):
    """POST returning (status, headers, body) without raising on 4xx/5xx —
    the 429/503 legs read the status and Retry-After like a client would."""
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, {k.lower(): v for k, v in r.headers.items()}, \
                json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, {k.lower(): v for k, v in e.headers.items()}, \
            json.loads(e.read() or b"{}")


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def test_tenant_rewarm_costs_zero_recompiles(fleet_model, tmp_path):
    """LRU thrash over tenants with identical shapes never recompiles:
    after the first tenant's warmup, every further load/evict/re-warm is
    a jit-cache hit (``tenant_load.jit_compiles == 0``)."""
    from hdbscan_tpu.utils.telemetry import compile_counter

    model_path, pts = fleet_model
    trace = str(tmp_path / "tenants.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    reg = TenantRegistry(
        {f"t{i}": model_path for i in range(4)},
        max_batch=64, lru_size=2, tracer=tracer,
    )
    X = pts[:16]
    reg.predict("t0", X)  # first load may compile (cold per-process cache)
    counter = compile_counter()
    before = counter()
    for tenant in ("t1", "t2", "t3", "t0", "t1"):  # loads, evicts, re-warms
        out, info = reg.predict(tenant, X)
        assert info["tenant"] == tenant and len(out[0]) == 16
    assert counter() - before == 0, "re-warm recompiled a seen shape"
    assert reg.generation("t0") == 2  # evicted, then re-warmed: new gen
    tracer.close()

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    loads = [e for e in events if e["stage"] == "tenant_load"]
    evicts = [e for e in events if e["stage"] == "tenant_evict"]
    assert len(loads) >= 6 and evicts, "expected LRU churn"
    assert all(e["resident"] <= 2 for e in evicts)
    assert all(e["jit_compiles"] == 0 for e in loads[1:])


def test_fleet_multi_tenant_matches_dedicated_server(fleet_model, tmp_path):
    from hdbscan_tpu.serve import ClusterModel
    from hdbscan_tpu.serve.server import ClusterServer

    model_path, pts = fleet_model
    tenants = [f"t{i}" for i in range(8)]
    tdir = tmp_path / "tenants"
    tdir.mkdir()
    blob = open(model_path, "rb").read()
    for t in tenants:
        (tdir / f"{t}.npz").write_bytes(blob)

    trace = str(tmp_path / "fleet.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    X = pts[:16].tolist()

    # the reference: a dedicated single-model server over the same artifact
    dedicated = ClusterServer(
        ClusterModel.load(model_path), max_batch=64, port=0,
    ).start()
    try:
        _, _, want = _post(
            f"http://{dedicated.host}:{dedicated.port}", "/predict",
            {"points": X},
        )
    finally:
        dedicated.close()

    router = FleetRouter(
        model_path, replicas=2, policy="consistent_hash",
        health_interval_s=0.3, tenants_dir=str(tdir),
        replica_args=["predict_batch=64", "tenant_lru=2", "tenant_quota=5"],
        tracer=tracer,
    )
    with router:
        base = f"http://{router.host}:{router.port}"
        homes = {}
        for _ in range(2):  # round 2 re-touches: LRU churn on each replica
            for t in tenants:
                status, headers, out = _post(
                    base, "/predict", {"points": X, "tenant": t}
                )
                assert status == 200, out
                assert out["tenant"] == t and out["generation"] >= 1
                # bitwise: the tenant answer IS the single-model answer
                for k in ("labels", "probabilities", "outlier_scores"):
                    assert out[k] == want[k], f"{t} diverged on {k}"
                homes.setdefault(t, headers["x-replica"])
                assert headers["x-replica"] == homes[t], (
                    f"tenant {t} flapped replicas"
                )
        assert set(homes.values()) <= {"0", "1"}

        # aggregated /metrics: per-replica + per-tenant series, evictions
        # from the LRU (8 tenants >> lru=2 per replica) already counted
        scrape = _get(base, "/metrics")
        parsed, errors = check_metrics.validate_exposition(scrape, "fleet")
        assert errors == [], errors
        samples = parsed["samples"]
        evicted = sum(
            v for (name, labels), v in samples.items()
            if name == "hdbscan_tpu_tenant_evictions_total"
        )
        assert evicted > 0
        fleet_series = {
            dict(labels)["replica"]
            for (name, labels), v in samples.items()
            if name == "hdbscan_tpu_fleet_requests_total"
        }
        assert {"0", "1"} <= fleet_series

        # quota: burst of 5 rps per tenant per replica — a hammered tenant
        # sheds 429 with a Retry-After hint, then recovers
        codes = []
        retry_after = None
        for _ in range(14):
            status, headers, _ = _post(
                base, "/predict", {"points": X[:1], "tenant": "t0"}
            )
            codes.append(status)
            if status == 429:
                retry_after = headers.get("retry-after")
        assert 429 in codes, f"quota never shed: {codes}"
        assert retry_after is not None and float(retry_after) > 0.0
        time.sleep(1.5)  # tokens refill at quota_rps
        status, _, _ = _post(base, "/predict", {"points": X[:1], "tenant": "t0"})
        assert status == 200

        # unknown tenant maps to a client error, not a fleet failure
        status, _, _ = _post(base, "/predict", {"points": X, "tenant": "nope"})
        assert status in (400, 404)
    assert router.drain_ok is True
    tracer.close()

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    routes = [e for e in events if e["stage"] == "fleet_route"]
    assert routes and all(e["policy"] == "consistent_hash" for e in routes)
    assert {e["status"] for e in routes} >= {200, 429}
    assert [e for e in events if e["stage"] == "replica_health"]


def test_fleet_chaos_sigkill_reroutes_and_replays_wal(fleet_model, tmp_path):
    model_path, pts = fleet_model
    trace = str(tmp_path / "chaos.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    rng = np.random.default_rng(3)
    router = FleetRouter(
        model_path, replicas=2, policy="least_loaded",
        health_interval_s=0.25, ingest=True,
        wal_root=str(tmp_path / "wal"),
        replica_args=["predict_batch=64"], tracer=tracer,
    )
    with router:
        base = f"http://{router.host}:{router.port}"
        acked = {"0": 0, "1": 0}

        def ingest(n_rows=16):
            batch = CENTERS[np.arange(n_rows) % 3] + rng.normal(
                0, 0.25, (n_rows, 3)
            )
            status, headers, out = _post(
                base, "/ingest", {"points": batch.tolist()}
            )
            if status == 200:
                acked[headers["x-replica"]] += out["rows"]
            return status

        # acked-before-the-crash rows: least_loaded breaks idle ties by
        # rid, so sequential ingests all land on replica 0 — the victim
        for _ in range(6):
            assert ingest() == 200
        assert acked["0"] > 0

        # concurrent /predict load across the kill window ------------------
        outcomes = {"served": 0, "shed": 0, "failed": 0, "offered": 0}
        lock = threading.Lock()
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                status, _, _ = _post(base, "/predict", {"points": pts[:8].tolist()})
                with lock:
                    outcomes["offered"] += 1
                    if status == 200:
                        outcomes["served"] += 1
                    elif status in (429, 503):
                        outcomes["shed"] += 1
                    else:
                        outcomes["failed"] += 1
                time.sleep(0.02)

        load = [threading.Thread(target=hammer, daemon=True) for _ in range(2)]
        for t in load:
            t.start()
        time.sleep(0.5)

        victim = router.replicas[0]
        os.kill(victim.proc.pid, signal.SIGKILL)
        # the very next routed request re-routes IN PLACE on connection
        # refused — strictly faster than the one-health-interval bound
        t0 = time.monotonic()
        status, headers, _ = _post(base, "/predict", {"points": pts[:8].tolist()})
        assert status == 200
        assert time.monotonic() - t0 < router.health_interval_s + 2.0
        assert headers["x-replica"] == "1"
        assert ingest() == 200  # un-sent ingest re-dispatches safely too

        # the router respawns the victim; its WAL replays the acked rows --
        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            h = router.health()["replicas"]["0"]
            if h["restarts"] >= 1 and h["up"]:
                break
            time.sleep(0.25)
        else:
            pytest.fail(f"replica 0 never respawned: {router.health()}")

        health = json.loads(_get(
            f"http://127.0.0.1:{router.replicas[0].port}", "/healthz"
        ))
        recover = health["stream"]["wal"]["last_recover"]
        assert recover["rows"] == acked["0"], "acked ingest rows lost"
        assert health["stream"]["rows_seen"] >= acked["0"]
        assert ingest() == 200  # the respawned replica takes traffic again

        stop.set()
        for t in load:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in load), "a request hung"
    assert router.drain_ok is True
    tracer.close()

    # reconciliation: every offered request reached a terminal outcome,
    # and the kill cost zero failures (predict re-routes freely)
    assert outcomes["offered"] > 0
    assert (outcomes["served"] + outcomes["shed"] + outcomes["failed"]
            == outcomes["offered"])
    assert outcomes["failed"] == 0, outcomes
    assert outcomes["shed"] == 0, outcomes

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    routes = [e for e in events if e["stage"] == "fleet_route"]
    # in-place re-route is visible as a served request with attempts > 1
    assert any(e["attempts"] > 1 and e["status"] == 200 for e in routes)
    probes = [e for e in events if e["stage"] == "replica_health"]
    assert any(not e["ok"] for e in probes)  # the probe saw the corpse
    assert any(e["restarts"] >= 1 for e in probes)
