"""End-to-end fleet acceptance (README "Fleet"): real replica
subprocesses behind the asyncio router.

Three legs:

- **Multi-tenant**: 8 tenants over a 2-replica consistent-hash fleet with
  a per-replica LRU (2) far under the tenant count — every tenant's
  response bitwise-matches a dedicated single-model server over the same
  artifact, evictions show up in the aggregated /metrics, a hammered
  tenant sheds 429 with a Retry-After hint, and the router trace passes
  ``scripts/check_trace.py``.
- **Re-warm economics** (in-process): evicting and re-warming tenants
  whose shapes were seen before costs zero jit compiles — the
  steady-state-no-recompile property survives multi-tenancy.
- **Chaos**: SIGKILL a replica mid-load — requests re-route in place
  (faster than one health interval), the replica respawns and replays its
  WAL with zero acked-ingest loss, no request hangs, and
  served+shed+failed reconciles with offered.
- **Elasticity** (README "Fleet control plane"): scale up from one
  replica — the standby AOT-warms from the shared persistent compile
  cache BEFORE ring admission (``warmup.jit_compiles == 0``,
  ``cache_hits > 0`` on its /healthz), serves bitwise-identical answers,
  then drains back down — with ``scale_event`` up+down in the trace and
  the scale counters in /metrics.
- **Fit-as-a-service**: four tenants served concurrently (through the
  shared zero-copy ArtifactStore) while a FitScheduler runs REAL fits
  and publishes generations through the registry's blue/green swap — a
  poisoned job fails in its worker without touching serving, zero
  request errors, no tenant ever observes a generation regression.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.fleet import FleetRouter, TenantRegistry
from hdbscan_tpu.models import hdbscan
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
from scripts import check_metrics, check_trace

CENTERS = np.asarray([(0.0, 0.0, 0.0), (6.0, 6.0, 6.0), (0.0, 8.0, 0.0)])


@pytest.fixture(scope="module")
def fleet_model(tmp_path_factory):
    """One small fitted artifact shared by every leg (the tenants are
    copies of it, so tenant warmups ride the process jit cache)."""
    rng = np.random.default_rng(11)
    pts = CENTERS[np.arange(360) % 3] + rng.normal(0, 0.25, (360, 3))
    params = HDBSCANParams(
        min_points=5, min_cluster_size=25, processing_units=512,
    )
    model = hdbscan.fit(pts, params).to_cluster_model(pts, params)
    path = str(tmp_path_factory.mktemp("fleet-model") / "model.npz")
    model.save(path)
    return path, pts


def _post(base, path, obj, timeout=120):
    """POST returning (status, headers, body) without raising on 4xx/5xx —
    the 429/503 legs read the status and Retry-After like a client would."""
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, {k.lower(): v for k, v in r.headers.items()}, \
                json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, {k.lower(): v for k, v in e.headers.items()}, \
            json.loads(e.read() or b"{}")


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def test_tenant_rewarm_costs_zero_recompiles(fleet_model, tmp_path):
    """LRU thrash over tenants with identical shapes never recompiles:
    after the first tenant's warmup, every further load/evict/re-warm is
    a jit-cache hit (``tenant_load.jit_compiles == 0``)."""
    from hdbscan_tpu.utils.telemetry import compile_counter

    model_path, pts = fleet_model
    trace = str(tmp_path / "tenants.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    reg = TenantRegistry(
        {f"t{i}": model_path for i in range(4)},
        max_batch=64, lru_size=2, tracer=tracer,
    )
    X = pts[:16]
    reg.predict("t0", X)  # first load may compile (cold per-process cache)
    counter = compile_counter()
    before = counter()
    for tenant in ("t1", "t2", "t3", "t0", "t1"):  # loads, evicts, re-warms
        out, info = reg.predict(tenant, X)
        assert info["tenant"] == tenant and len(out[0]) == 16
    assert counter() - before == 0, "re-warm recompiled a seen shape"
    assert reg.generation("t0") == 2  # evicted, then re-warmed: new gen
    tracer.close()

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    loads = [e for e in events if e["stage"] == "tenant_load"]
    evicts = [e for e in events if e["stage"] == "tenant_evict"]
    assert len(loads) >= 6 and evicts, "expected LRU churn"
    assert all(e["resident"] <= 2 for e in evicts)
    assert all(e["jit_compiles"] == 0 for e in loads[1:])


def test_fleet_multi_tenant_matches_dedicated_server(fleet_model, tmp_path):
    from hdbscan_tpu.serve import ClusterModel
    from hdbscan_tpu.serve.server import ClusterServer

    model_path, pts = fleet_model
    tenants = [f"t{i}" for i in range(8)]
    tdir = tmp_path / "tenants"
    tdir.mkdir()
    blob = open(model_path, "rb").read()
    for t in tenants:
        (tdir / f"{t}.npz").write_bytes(blob)

    trace = str(tmp_path / "fleet.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    X = pts[:16].tolist()

    # the reference: a dedicated single-model server over the same artifact
    dedicated = ClusterServer(
        ClusterModel.load(model_path), max_batch=64, port=0,
    ).start()
    try:
        _, _, want = _post(
            f"http://{dedicated.host}:{dedicated.port}", "/predict",
            {"points": X},
        )
    finally:
        dedicated.close()

    router = FleetRouter(
        model_path, replicas=2, policy="consistent_hash",
        health_interval_s=0.3, tenants_dir=str(tdir),
        replica_args=["predict_batch=64", "tenant_lru=2", "tenant_quota=5"],
        tracer=tracer,
    )
    with router:
        base = f"http://{router.host}:{router.port}"
        homes = {}
        for _ in range(2):  # round 2 re-touches: LRU churn on each replica
            for t in tenants:
                status, headers, out = _post(
                    base, "/predict", {"points": X, "tenant": t}
                )
                assert status == 200, out
                assert out["tenant"] == t and out["generation"] >= 1
                # bitwise: the tenant answer IS the single-model answer
                for k in ("labels", "probabilities", "outlier_scores"):
                    assert out[k] == want[k], f"{t} diverged on {k}"
                homes.setdefault(t, headers["x-replica"])
                assert headers["x-replica"] == homes[t], (
                    f"tenant {t} flapped replicas"
                )
        assert set(homes.values()) <= {"0", "1"}

        # aggregated /metrics: per-replica + per-tenant series, evictions
        # from the LRU (8 tenants >> lru=2 per replica) already counted
        scrape = _get(base, "/metrics")
        parsed, errors = check_metrics.validate_exposition(scrape, "fleet")
        assert errors == [], errors
        samples = parsed["samples"]
        evicted = sum(
            v for (name, labels), v in samples.items()
            if name == "hdbscan_tpu_tenant_evictions_total"
        )
        assert evicted > 0
        fleet_series = {
            dict(labels)["replica"]
            for (name, labels), v in samples.items()
            if name == "hdbscan_tpu_fleet_requests_total"
        }
        assert {"0", "1"} <= fleet_series

        # quota: burst of 5 rps per tenant per replica — a hammered tenant
        # sheds 429 with a Retry-After hint, then recovers
        codes = []
        retry_after = None
        for _ in range(14):
            status, headers, _ = _post(
                base, "/predict", {"points": X[:1], "tenant": "t0"}
            )
            codes.append(status)
            if status == 429:
                retry_after = headers.get("retry-after")
        assert 429 in codes, f"quota never shed: {codes}"
        assert retry_after is not None and float(retry_after) > 0.0
        time.sleep(1.5)  # tokens refill at quota_rps
        status, _, _ = _post(base, "/predict", {"points": X[:1], "tenant": "t0"})
        assert status == 200

        # unknown tenant maps to a client error, not a fleet failure
        status, _, _ = _post(base, "/predict", {"points": X, "tenant": "nope"})
        assert status in (400, 404)
    assert router.drain_ok is True
    tracer.close()

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    routes = [e for e in events if e["stage"] == "fleet_route"]
    assert routes and all(e["policy"] == "consistent_hash" for e in routes)
    assert {e["status"] for e in routes} >= {200, 429}
    assert [e for e in events if e["stage"] == "replica_health"]


def test_fleet_chaos_sigkill_reroutes_and_replays_wal(fleet_model, tmp_path):
    model_path, pts = fleet_model
    trace = str(tmp_path / "chaos.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    rng = np.random.default_rng(3)
    router = FleetRouter(
        model_path, replicas=2, policy="least_loaded",
        health_interval_s=0.25, ingest=True,
        wal_root=str(tmp_path / "wal"),
        replica_args=["predict_batch=64"], tracer=tracer,
    )
    with router:
        base = f"http://{router.host}:{router.port}"
        acked = {"0": 0, "1": 0}

        def ingest(n_rows=16):
            batch = CENTERS[np.arange(n_rows) % 3] + rng.normal(
                0, 0.25, (n_rows, 3)
            )
            status, headers, out = _post(
                base, "/ingest", {"points": batch.tolist()}
            )
            if status == 200:
                acked[headers["x-replica"]] += out["rows"]
            return status

        # acked-before-the-crash rows: least_loaded breaks idle ties by
        # rid, so sequential ingests all land on replica 0 — the victim
        for _ in range(6):
            assert ingest() == 200
        assert acked["0"] > 0

        # concurrent /predict load across the kill window ------------------
        outcomes = {"served": 0, "shed": 0, "failed": 0, "offered": 0}
        lock = threading.Lock()
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                status, _, _ = _post(base, "/predict", {"points": pts[:8].tolist()})
                with lock:
                    outcomes["offered"] += 1
                    if status == 200:
                        outcomes["served"] += 1
                    elif status in (429, 503):
                        outcomes["shed"] += 1
                    else:
                        outcomes["failed"] += 1
                time.sleep(0.02)

        load = [threading.Thread(target=hammer, daemon=True) for _ in range(2)]
        for t in load:
            t.start()
        time.sleep(0.5)

        victim = router.replicas[0]
        os.kill(victim.proc.pid, signal.SIGKILL)
        # the very next routed request re-routes IN PLACE on connection
        # refused — strictly faster than the one-health-interval bound
        t0 = time.monotonic()
        status, headers, _ = _post(base, "/predict", {"points": pts[:8].tolist()})
        assert status == 200
        assert time.monotonic() - t0 < router.health_interval_s + 2.0
        assert headers["x-replica"] == "1"
        assert ingest() == 200  # un-sent ingest re-dispatches safely too

        # the router respawns the victim; its WAL replays the acked rows --
        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            h = router.health()["replicas"]["0"]
            if h["restarts"] >= 1 and h["up"]:
                break
            time.sleep(0.25)
        else:
            pytest.fail(f"replica 0 never respawned: {router.health()}")

        health = json.loads(_get(
            f"http://127.0.0.1:{router.replicas[0].port}", "/healthz"
        ))
        recover = health["stream"]["wal"]["last_recover"]
        assert recover["rows"] == acked["0"], "acked ingest rows lost"
        assert health["stream"]["rows_seen"] >= acked["0"]
        assert ingest() == 200  # the respawned replica takes traffic again

        stop.set()
        for t in load:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in load), "a request hung"
    assert router.drain_ok is True
    tracer.close()

    # reconciliation: every offered request reached a terminal outcome,
    # and the kill cost zero failures (predict re-routes freely)
    assert outcomes["offered"] > 0
    assert (outcomes["served"] + outcomes["shed"] + outcomes["failed"]
            == outcomes["offered"])
    assert outcomes["failed"] == 0, outcomes
    assert outcomes["shed"] == 0, outcomes

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    routes = [e for e in events if e["stage"] == "fleet_route"]
    # in-place re-route is visible as a served request with attempts > 1
    assert any(e["attempts"] > 1 and e["status"] == 200 for e in routes)
    probes = [e for e in events if e["stage"] == "replica_health"]
    assert any(not e["ok"] for e in probes)  # the probe saw the corpse
    assert any(e["restarts"] >= 1 for e in probes)


def test_fleet_scale_up_warm_spawn_and_scale_down(fleet_model, tmp_path):
    """Elasticity round trip over real subprocesses: the scaled-up standby
    replays replica 0's compiles from the router-injected persistent cache
    (warm-spawn ``jit_compiles == 0`` — the control plane's AOT-warm
    contract), is admitted to the ring only after a healthy probe, serves
    the same bits, and scale-down drains it without dropping requests."""
    model_path, pts = fleet_model
    trace = str(tmp_path / "scale.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    cache_dir = str(tmp_path / "xla-cache")
    X = pts[:16].tolist()
    router = FleetRouter(
        model_path, replicas=1, policy="least_loaded",
        health_interval_s=0.3, compile_cache=cache_dir,
        replica_args=["predict_batch=64"], tracer=tracer,
    )
    with router:
        base = f"http://{router.host}:{router.port}"
        status, _, want = _post(base, "/predict", {"points": X})
        assert status == 200

        # replica 0 spawned cold: its AOT warmup PAID compiles and seeded
        # the shared cache directory every later spawn inherits
        h0 = json.loads(_get(
            f"http://127.0.0.1:{router.replicas[0].port}", "/healthz"
        ))
        assert h0["warmup"]["jit_compiles"] > 0, h0["warmup"]
        assert os.listdir(cache_dir), "replica 0 never seeded the cache"

        rid = router.scale_up(reason="manual", timeout=180)
        assert rid == "1"
        assert [r.rid for r in router.replicas] == ["0", "1"]
        assert router.health()["replicas"]["1"]["up"] is True

        # the warm-spawn contract: the standby's warmup was served
        # entirely from the persistent cache — zero compiles paid
        h1 = json.loads(_get(
            f"http://127.0.0.1:{router.replicas[1].port}", "/healthz"
        ))
        assert h1["warmup"]["jit_compiles"] == 0, h1["warmup"]
        assert h1["warmup"]["cache_hits"] > 0, h1["warmup"]
        assert h1["warmup"]["buckets"] == h0["warmup"]["buckets"]

        # the admitted standby answers bitwise the same as the anchor
        status, _, out = _post(
            f"http://127.0.0.1:{router.replicas[1].port}", "/predict",
            {"points": X},
        )
        assert status == 200
        for k in ("labels", "probabilities", "outlier_scores"):
            assert out[k] == want[k], f"standby diverged on {k}"

        # under concurrent load, least_loaded spills onto the new replica
        # (sequential idle requests always tie-break to rid 0)
        served_by = set()
        burst_errors = []
        lock = threading.Lock()

        def one_shot():
            status, headers, out = _post(base, "/predict", {"points": X})
            with lock:
                if status != 200 or out["labels"] != want["labels"]:
                    burst_errors.append((status, out))
                else:
                    served_by.add(headers["x-replica"])

        burst = [threading.Thread(target=one_shot) for _ in range(16)]
        for th in burst:
            th.start()
        for th in burst:
            th.join(timeout=60)
        assert burst_errors == [], burst_errors[:3]
        assert served_by == {"0", "1"}, served_by

        # scale counters surfaced through the aggregated scrape
        scrape = _get(base, "/metrics")
        parsed, errors = check_metrics.validate_exposition(scrape, "fleet")
        assert errors == [], errors
        ups = sum(
            v for (name, labels), v in parsed["samples"].items()
            if name == "hdbscan_tpu_scale_events_total"
            and dict(labels) == {"direction": "up", "ok": "true"}
        )
        assert ups == 1

        assert router.scale_down(rid, reason="manual", timeout=60) is True
        assert [r.rid for r in router.replicas] == ["0"]
        status, headers, out = _post(base, "/predict", {"points": X})
        assert status == 200 and headers["x-replica"] == "0"
        # the last replica is an anchor: scale-down refuses to drop it
        assert router.scale_down(reason="anchor", timeout=60) is False
    assert router.drain_ok is True
    tracer.close()

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    scales = [e for e in events if e["stage"] == "scale_event"]
    ups = [e for e in scales if e["direction"] == "up"]
    downs = [e for e in scales if e["direction"] == "down"]
    assert len(ups) == 1 and ups[0]["ok"] and ups[0]["replicas"] == 2
    assert len(downs) == 1 and downs[0]["ok"] and downs[0]["replicas"] == 1
    assert ups[0]["replica"] == downs[0]["replica"] == "1"


def test_fit_as_a_service_four_tenants_no_mixed_generations(
        fleet_model, tmp_path):
    """Fit-as-a-service over a live multi-tenant registry: REAL fits
    publish new generations through the blue/green swap while four
    tenants are hammered concurrently — zero request errors, per-thread
    generation observations never regress, and a poisoned job fails in
    its worker without touching its tenant's serving path."""
    from hdbscan_tpu.fleet import ArtifactStore, FitScheduler, TenantRegistry
    from hdbscan_tpu.fleet.jobs import ShedRequest  # noqa: F401 (contract)

    model_path, pts = fleet_model
    trace = str(tmp_path / "fitsvc.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    tenants = [f"t{i}" for i in range(4)]
    params = HDBSCANParams(
        min_points=5, min_cluster_size=25, processing_units=512,
    )
    store = ArtifactStore(spool_dir=str(tmp_path / "spool"), tracer=tracer)
    reg = TenantRegistry(
        {t: model_path for t in tenants}, max_batch=64, lru_size=4,
        tracer=tracer, artifact_store=store,
    )
    sched = FitScheduler(
        str(tmp_path / "models"), params=params,
        publish=lambda tenant, path, model: reg.swap(tenant, path),
        workers=2, tracer=tracer,
    )
    X = pts[:16]
    for t in tenants:
        reg.predict(t, X)  # warm every tenant before the churn

    errors_seen = []
    seen_gens = {(t, w): [] for t in tenants for w in range(3)}
    stop = threading.Event()

    def hammer(w):
        rng = np.random.default_rng(w)
        while not stop.is_set():
            t = tenants[rng.integers(len(tenants))]
            try:
                out, info = reg.predict(t, X)
                assert info["tenant"] == t and len(out[0]) == 16
                seen_gens[(t, w)].append(info["generation"])
            except Exception as exc:  # noqa: BLE001 — the assertion target
                errors_seen.append(f"{t}: {type(exc).__name__}: {exc}")
            time.sleep(0.002)

    threads = [
        threading.Thread(target=hammer, args=(w,), daemon=True)
        for w in range(3)
    ]
    for th in threads:
        th.start()
    try:
        # one real refit per tenant, concurrent with serving
        rng = np.random.default_rng(29)
        jobs = []
        for t in tenants:
            fresh = CENTERS[np.arange(360) % 3] + rng.normal(0, 0.25, (360, 3))
            jobs.append(sched.submit(t, fresh, reason="drift"))
        # the poison: un-fittable rows crash INSIDE the worker
        poison = sched.submit("t0", np.array([["nope"] * 3] * 360))
        assert sched.join(timeout=600) is True
        time.sleep(0.3)  # let the hammers observe the published generations
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=30)
        sched.close()
    assert not any(th.is_alive() for th in threads), "a predict hung"
    assert errors_seen == [], errors_seen[:5]

    # every good job published; the poison failed without collateral
    for j in jobs:
        assert j.state == "published" and j.generation == 2, (
            j.job_id, j.state, j.error,
        )
    assert poison.state == "failed" and poison.error
    assert sched.stats()["published"] == 4 and sched.stats()["failed"] == 1
    # t0 still serves, on the GOOD generation the poison never displaced
    out, info = reg.predict("t0", X)
    assert info["generation"] == reg.generation("t0") >= 2
    assert len(out[0]) == 16

    # no thread ever saw a tenant's generation move backwards, and every
    # tenant's final observation is the post-swap generation
    for (t, w), gens in seen_gens.items():
        assert gens == sorted(gens), f"{t} regressed in thread {w}: {gens}"
    for t in tenants:
        finals = [gens[-1] for (tt, _), gens in seen_gens.items()
                  if tt == t and gens]
        assert finals and max(finals) >= 2, (t, finals)
    tracer.close()

    events, errors = check_trace.validate_trace(trace)
    assert not errors, errors
    fit_events = [e for e in events if e["stage"] == "fit_job"]
    published = [e for e in fit_events if e["state"] == "published"]
    failed = [e for e in fit_events if e["state"] == "failed"]
    assert len(published) == 4
    assert {e["tenant"] for e in published} == set(tenants)
    assert all(e["generation"] == 2 for e in published)
    assert len(failed) == 1 and failed[0]["tenant"] == "t0"
    # the shared store absorbed the tenant fan-out: the seed artifact
    # loaded once (miss) and every other tenant's first touch was a hit
    arts = [e for e in events if e["stage"] == "artifact_map"]
    assert sum(1 for e in arts if not e["hit"]) >= 1
    assert sum(1 for e in arts if e["hit"]) >= 3
