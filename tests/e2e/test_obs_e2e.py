"""Deep-observability end-to-end (README "Observability").

Four legs:

- **CLI fit**: a real ``python -m hdbscan_tpu`` subprocess with
  ``--trace-out``/``--report``/``--assert-not-replicated`` and the
  watchdog armed. The trace must satisfy ``scripts/check_trace.py``'s obs
  schemas, the report must carry the per-phase memory watermark table,
  and the replication gate must pass cleanly on the single-device run
  (the 8-device trip/pass legs live in ``tests/unit/test_obs.py``).
- **Sharded CLI timeline/roofline/flight**: the forced-8-device sharded
  fit with the mesh timeline armed — per-device ``device_timeline``
  events telescoping within 1e-6, a ``hdbscan-tpu-report/3`` with
  timeline + roofline sections (bound classification for every traced
  phase, ``cpu_smoke``-tagged), and zero flight bundles on the healthy
  run (``scripts/check_flight.py --allow-empty`` green).
- **200k sharded scan** (slow lane): the ISSUE-scale mesh leg — the
  exact ring k-NN core-distance scan over 200k points, with the same
  telescoping/roofline acceptance bar.
- **Fleet join** (slow lane): real replica subprocesses behind the router
  with ``replica_trace_dir`` set — every routed request's ``router_span``
  must join exactly one replica ``request_span`` on the propagated
  ``X-Request-Id`` (100% causal-chain reconstruction), both through
  ``obs.merge_fleet_traces`` and the ``check_trace.py --join`` CLI.
"""

import json
import math
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from hdbscan_tpu import HDBSCANParams, obs
from hdbscan_tpu.utils.telemetry import REPORT_SCHEMA
from scripts import check_flight, check_trace

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _blobs_csv(path, n=120, seed=5):
    rng = np.random.default_rng(seed)
    centers = np.asarray([(0.0, 0.0, 0.0), (5.0, 5.0, 5.0)])
    pts = centers[np.arange(n) % 2] + rng.normal(0, 0.2, (n, 3))
    np.savetxt(path, pts, delimiter=",")
    return pts


def test_cli_fit_deep_observability(tmp_path):
    csv = str(tmp_path / "pts.csv")
    trace = str(tmp_path / "trace.jsonl")
    report = str(tmp_path / "report.json")
    _blobs_csv(csv)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Drop conftest's 8-device forcing: this leg is the single-device CLI
    # story (gate passes via the one-device bypass, recorded in the event).
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "hdbscan_tpu",
            f"file={csv}", "minPts=4", "minClSize=4",
            f"out_dir={tmp_path}",
            "--trace-out", trace, "--report", report,
            "--assert-not-replicated", "watchdog=30", "heartbeat=0.2",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    events, errors = check_trace.validate_trace(trace)
    assert errors == [], errors
    stages = {e["stage"] for e in events}
    assert {"mem_sample", "mem_phase_peak", "heartbeat",
            "replication_gate"} <= stages
    # No stall on a healthy run: the watchdog produced zero dumps.
    assert "watchdog_stall" not in stages

    gates = [e for e in events if e["stage"] == "replication_gate"]
    assert len(gates) == 1 and gates[0]["ok"] is True
    assert gates[0]["phases"] >= 1

    beats = [e for e in events if e["stage"] == "heartbeat"]
    assert beats and all(0.0 <= e["progress"] <= 1.0 for e in beats)

    with open(report, encoding="utf-8") as f:
        rep = json.load(f)
    assert rep["schema"] == REPORT_SCHEMA
    watermarks = rep["memory"]["watermarks"]
    assert watermarks, "report carries no per-phase memory watermarks"
    peaks_by_phase = {
        e["phase"]: e for e in events if e["stage"] == "mem_phase_peak"
    }
    for phase, wm in watermarks.items():
        assert phase in peaks_by_phase
        assert wm["samples"] >= 2
        assert wm["max_device_bytes"] >= 0
        assert wm["max_device_bytes"] <= wm["total_bytes"]
    # The report pairs with its trace under the full validator.
    _, rep_errors = check_trace.validate_report(report, trace_events=events)
    assert rep_errors == [], rep_errors


def _assert_timeline_report(events, rep, n_dev=8):
    """The shared ISSUE acceptance bar for a traced mesh run: per-device
    segments telescope to phase walls within 1e-6, the report's timeline
    table covers every traced phase, and the roofline section classifies
    each one compute/memory/comm-bound under honest ``cpu_smoke`` tags."""
    tl = [e for e in events if e["stage"] == "device_timeline"]
    assert tl, "run emitted no device_timeline events"
    assert {e["device"] for e in tl} == set(range(n_dev))
    for e in tl:
        total = e["compute_s"] + e["comm_s"] + e["host_s"]
        assert math.isclose(total, e.get("wall_s"), rel_tol=0.0, abs_tol=1e-6)
        assert e["attribution"] == "model"
    assert any(e["comm_bytes"] > 0 for e in tl), "no comm bytes attributed"

    assert rep["schema"] == REPORT_SCHEMA
    table = rep["timeline"]
    traced_phases = {e["phase"] for e in tl}
    assert set(table) == traced_phases
    for row in table.values():
        assert row["rounds"] >= 1 and row["devices"] == n_dev
        assert 0.0 <= row["comm_frac"] <= 1.0
        assert row["skew"] >= 1.0

    roof = rep["roofline"]
    assert "cpu_smoke" in roof["tags"], "forced CPU mesh must stay honest"
    assert roof["ridge_intensity"] > 0
    assert traced_phases <= set(roof["phases"])
    for row in roof["phases"].values():
        assert row["bound"] in ("compute", "memory", "comm")


def test_cli_sharded_fit_timeline_roofline_flight(tmp_path):
    """Sharded fit on the forced-8-device mesh with the full deep-obs
    surface armed: mesh timeline events valid and telescoping, report/3
    timeline + roofline sections, and the always-on flight recorder
    writing *nothing* on a healthy run."""
    from hdbscan_tpu.cli import main

    rng = np.random.default_rng(11)
    pts = np.concatenate(
        [rng.normal(0.0, 1.0, (1024, 2)), rng.normal(8.0, 1.0, (1024, 2))]
    )
    rng.shuffle(pts)
    csv = tmp_path / "blobs.csv"
    np.savetxt(csv, pts, delimiter=",")
    trace = tmp_path / "trace.jsonl"
    report = tmp_path / "report.json"
    flight_dir = tmp_path / "flight"
    rc = main(
        [
            f"file={csv}",
            "minPts=5",
            "minClSize=10",
            "fit_sharding=sharded",
            # Pin the exact one-program leg (sharded routing honors
            # processing_units now; above it the MR pipeline runs).
            "processing_units=4096",
            "--trace-out", str(trace),
            "--report", str(report),
            "--flight-dir", str(flight_dir),
            f"out_dir={tmp_path}",
        ]
    )
    assert rc == 0

    events, errors = check_trace.validate_trace(str(trace))
    assert errors == [], errors
    with open(report, encoding="utf-8") as f:
        rep = json.load(f)
    _assert_timeline_report(events, rep)
    _, rep_errors = check_trace.validate_report(str(report), trace_events=events)
    assert rep_errors == [], rep_errors

    # Healthy run: the flight recorder dumped nothing — it creates the
    # directory lazily at first dump, so the dir itself must not exist ...
    assert not flight_dir.exists()
    # ... and the validator agrees, but only under --allow-empty.
    flight_dir.mkdir()
    assert check_flight.main(["--allow-empty", str(flight_dir)]) == 0
    assert check_flight.main([str(flight_dir)]) == 1


@pytest.mark.slow
def test_sharded_200k_scan_timeline_roofline(tmp_path):
    """The ISSUE-scale mesh leg: 200k points through the exact ring
    k-NN core-distance scan (~300s on the shared-core CPU smoke mesh;
    the full Boruvka fit adds O(n^2) *per round* and the sharded
    rp-forest tier's panel sweep is slower still here — 400s at 25k —
    so this is the largest honest 200k program, same precedent as
    test_sharded_scan.py's 100k leg), with the report/3 timeline +
    roofline acceptance bar."""
    from hdbscan_tpu.parallel.mesh import get_mesh
    from hdbscan_tpu.parallel.shard import shard_core_distances
    from hdbscan_tpu.utils.telemetry import build_report
    from hdbscan_tpu.utils.tracing import JsonlSink, Tracer

    rng = np.random.default_rng(3)
    centers = rng.uniform(-40.0, 40.0, size=(32, 3))
    data = centers[np.arange(200_000) % 32] + rng.normal(
        0, 0.5, (200_000, 3)
    )
    trace_path = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace_path)])
    from hdbscan_tpu.obs.timeline import TimelineRecorder

    rec = TimelineRecorder(trace=tracer)
    with obs.installed(timeline=rec):
        core = shard_core_distances(
            data, 5, mesh=get_mesh(), trace=tracer, index="exact"
        )
    tracer.close()
    core = np.asarray(core)
    assert core.shape == (200_000,)
    assert np.all(np.isfinite(core)) and np.all(core > 0)

    rep = build_report(tracer, timeline=rec.phase_table())
    events, errors = check_trace.validate_trace(trace_path)
    assert errors == [], errors
    _assert_timeline_report(events, rep)
    # 200k rows over 8 shards: every traced round moved real panel bytes.
    tl = [e for e in events if e["stage"] == "device_timeline"]
    assert sum(e["comm_bytes"] for e in tl) >= 200_000 * 3 * 4


@pytest.fixture(scope="module")
def obs_fleet_model(tmp_path_factory):
    from hdbscan_tpu.models import hdbscan

    rng = np.random.default_rng(17)
    centers = np.asarray([(0.0, 0.0, 0.0), (6.0, 6.0, 6.0), (0.0, 8.0, 0.0)])
    pts = centers[np.arange(360) % 3] + rng.normal(0, 0.25, (360, 3))
    params = HDBSCANParams(
        min_points=5, min_cluster_size=25, processing_units=512,
    )
    model = hdbscan.fit(pts, params).to_cluster_model(pts, params)
    path = str(tmp_path_factory.mktemp("obs-fleet") / "model.npz")
    model.save(path)
    return path, pts


@pytest.mark.slow
def test_fleet_router_replica_join_is_complete(obs_fleet_model, tmp_path):
    from hdbscan_tpu.fleet import FleetRouter
    from hdbscan_tpu.utils.tracing import JsonlSink, Tracer

    model_path, pts = obs_fleet_model
    router_trace = str(tmp_path / "router.jsonl")
    replica_dir = str(tmp_path / "replica-traces")
    tracer = Tracer(sinks=[JsonlSink(router_trace)])
    router = FleetRouter(
        model_path, replicas=2, policy="least_loaded",
        health_interval_s=0.5, replica_args=["predict_batch=64"],
        tracer=tracer, replica_trace_dir=replica_dir,
    )
    X = pts[:8].tolist()
    seen_ids = []
    with router:
        base = f"http://{router.host}:{router.port}"
        for i in range(24):
            headers = {"Content-Type": "application/json"}
            if i % 4 == 0:  # every 4th request supplies its own id ...
                headers["X-Request-Id"] = f"client-{i}"
            req = urllib.request.Request(
                base + "/predict", json.dumps({"points": X}).encode(), headers
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                assert r.status == 200
                rid = r.headers["x-request-id"]
            assert rid, "response lost its X-Request-Id"
            if i % 4 == 0:  # ... and gets the SAME id back (propagated)
                assert rid == f"client-{i}"
            seen_ids.append(rid)
    tracer.close()
    assert len(set(seen_ids)) == 24  # ids are unique across the run

    replica_traces = sorted(
        os.path.join(replica_dir, f) for f in os.listdir(replica_dir)
    )
    assert len(replica_traces) == 2

    # 100% causal-chain reconstruction, by the library join ...
    merged = obs.merge_fleet_traces(router_trace, replica_traces)
    join = merged["join"]
    assert join["complete"] is True, join
    assert join["matched"] == join["replied"] == 24
    assert join["orphans"] == [] and join["duplicates"] == []
    assert merged["router"]["events"] > 0
    assert all(r["events"] > 0 for r in merged["replicas"].values())

    # ... and by the standalone validator CLI (validates both sides too).
    assert check_trace.join_fleet(router_trace, replica_traces) == 0

    # The router's spans carry the client-supplied ids bitwise.
    events, errors = check_trace.validate_trace(router_trace)
    assert errors == [], errors
    span_ids = {
        e["request_id"] for e in events if e["stage"] == "router_span"
    }
    assert span_ids == set(seen_ids)
