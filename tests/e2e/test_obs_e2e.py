"""Deep-observability end-to-end (README "Observability").

Two legs:

- **CLI fit**: a real ``python -m hdbscan_tpu`` subprocess with
  ``--trace-out``/``--report``/``--assert-not-replicated`` and the
  watchdog armed. The trace must satisfy ``scripts/check_trace.py``'s obs
  schemas, the report must carry the per-phase memory watermark table
  (schema ``hdbscan-tpu-report/2``), and the replication gate must pass
  cleanly on the single-device run (the 8-device trip/pass legs live in
  ``tests/unit/test_obs.py``).
- **Fleet join** (slow lane): real replica subprocesses behind the router
  with ``replica_trace_dir`` set — every routed request's ``router_span``
  must join exactly one replica ``request_span`` on the propagated
  ``X-Request-Id`` (100% causal-chain reconstruction), both through
  ``obs.merge_fleet_traces`` and the ``check_trace.py --join`` CLI.
"""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from hdbscan_tpu import HDBSCANParams, obs
from hdbscan_tpu.utils.telemetry import REPORT_SCHEMA
from scripts import check_trace

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _blobs_csv(path, n=120, seed=5):
    rng = np.random.default_rng(seed)
    centers = np.asarray([(0.0, 0.0, 0.0), (5.0, 5.0, 5.0)])
    pts = centers[np.arange(n) % 2] + rng.normal(0, 0.2, (n, 3))
    np.savetxt(path, pts, delimiter=",")
    return pts


def test_cli_fit_deep_observability(tmp_path):
    csv = str(tmp_path / "pts.csv")
    trace = str(tmp_path / "trace.jsonl")
    report = str(tmp_path / "report.json")
    _blobs_csv(csv)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Drop conftest's 8-device forcing: this leg is the single-device CLI
    # story (gate passes via the one-device bypass, recorded in the event).
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "hdbscan_tpu",
            f"file={csv}", "minPts=4", "minClSize=4",
            f"out_dir={tmp_path}",
            "--trace-out", trace, "--report", report,
            "--assert-not-replicated", "watchdog=30", "heartbeat=0.2",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    events, errors = check_trace.validate_trace(trace)
    assert errors == [], errors
    stages = {e["stage"] for e in events}
    assert {"mem_sample", "mem_phase_peak", "heartbeat",
            "replication_gate"} <= stages
    # No stall on a healthy run: the watchdog produced zero dumps.
    assert "watchdog_stall" not in stages

    gates = [e for e in events if e["stage"] == "replication_gate"]
    assert len(gates) == 1 and gates[0]["ok"] is True
    assert gates[0]["phases"] >= 1

    beats = [e for e in events if e["stage"] == "heartbeat"]
    assert beats and all(0.0 <= e["progress"] <= 1.0 for e in beats)

    with open(report, encoding="utf-8") as f:
        rep = json.load(f)
    assert rep["schema"] == REPORT_SCHEMA
    watermarks = rep["memory"]["watermarks"]
    assert watermarks, "report carries no per-phase memory watermarks"
    peaks_by_phase = {
        e["phase"]: e for e in events if e["stage"] == "mem_phase_peak"
    }
    for phase, wm in watermarks.items():
        assert phase in peaks_by_phase
        assert wm["samples"] >= 2
        assert wm["max_device_bytes"] >= 0
        assert wm["max_device_bytes"] <= wm["total_bytes"]
    # The report pairs with its trace under the full validator.
    _, rep_errors = check_trace.validate_report(report, trace_events=events)
    assert rep_errors == [], rep_errors


@pytest.fixture(scope="module")
def obs_fleet_model(tmp_path_factory):
    from hdbscan_tpu.models import hdbscan

    rng = np.random.default_rng(17)
    centers = np.asarray([(0.0, 0.0, 0.0), (6.0, 6.0, 6.0), (0.0, 8.0, 0.0)])
    pts = centers[np.arange(360) % 3] + rng.normal(0, 0.25, (360, 3))
    params = HDBSCANParams(
        min_points=5, min_cluster_size=25, processing_units=512,
    )
    model = hdbscan.fit(pts, params).to_cluster_model(pts, params)
    path = str(tmp_path_factory.mktemp("obs-fleet") / "model.npz")
    model.save(path)
    return path, pts


@pytest.mark.slow
def test_fleet_router_replica_join_is_complete(obs_fleet_model, tmp_path):
    from hdbscan_tpu.fleet import FleetRouter
    from hdbscan_tpu.utils.tracing import JsonlSink, Tracer

    model_path, pts = obs_fleet_model
    router_trace = str(tmp_path / "router.jsonl")
    replica_dir = str(tmp_path / "replica-traces")
    tracer = Tracer(sinks=[JsonlSink(router_trace)])
    router = FleetRouter(
        model_path, replicas=2, policy="least_loaded",
        health_interval_s=0.5, replica_args=["predict_batch=64"],
        tracer=tracer, replica_trace_dir=replica_dir,
    )
    X = pts[:8].tolist()
    seen_ids = []
    with router:
        base = f"http://{router.host}:{router.port}"
        for i in range(24):
            headers = {"Content-Type": "application/json"}
            if i % 4 == 0:  # every 4th request supplies its own id ...
                headers["X-Request-Id"] = f"client-{i}"
            req = urllib.request.Request(
                base + "/predict", json.dumps({"points": X}).encode(), headers
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                assert r.status == 200
                rid = r.headers["x-request-id"]
            assert rid, "response lost its X-Request-Id"
            if i % 4 == 0:  # ... and gets the SAME id back (propagated)
                assert rid == f"client-{i}"
            seen_ids.append(rid)
    tracer.close()
    assert len(set(seen_ids)) == 24  # ids are unique across the run

    replica_traces = sorted(
        os.path.join(replica_dir, f) for f in os.listdir(replica_dir)
    )
    assert len(replica_traces) == 2

    # 100% causal-chain reconstruction, by the library join ...
    merged = obs.merge_fleet_traces(router_trace, replica_traces)
    join = merged["join"]
    assert join["complete"] is True, join
    assert join["matched"] == join["replied"] == 24
    assert join["orphans"] == [] and join["duplicates"] == []
    assert merged["router"]["events"] > 0
    assert all(r["events"] > 0 for r in merged["replicas"].values())

    # ... and by the standalone validator CLI (validates both sides too).
    assert check_trace.join_fleet(router_trace, replica_traces) == 0

    # The router's spans carry the client-supplied ids bitwise.
    events, errors = check_trace.validate_trace(router_trace)
    assert errors == [], errors
    span_ids = {
        e["request_id"] for e in events if e["stage"] == "router_span"
    }
    assert span_ids == set(seen_ids)
