"""End-to-end streaming acceptance: a 10k-point stream with an injected
distribution shift flows into a served 5k-fit model; the drift detector
flags the shift, a background re-fit publishes a generation-2 artifact,
and the blue/green hot-swap lands with zero failed and zero mixed-model
requests under concurrent /predict load. Post-swap quality is checked as
ARI on shifted data against a from-scratch fit over the same distribution,
and the whole trace passes scripts/check_trace.py.

The full leg streams 10k points through a live server (~a minute on CPU),
so it rides the documented ``slow`` lane — excluded from tier-1's
``-m 'not slow'`` run; exercised via ``pytest -m slow``."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import hdbscan, mr_hdbscan
from hdbscan_tpu.serve import ClusterModel, approximate_predict
from hdbscan_tpu.serve.server import ClusterServer
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from hdbscan_tpu.utils.tracing import JsonlSink, Tracer
from scripts import check_trace

#: Three fit-time blobs plus one the stream drifts onto.
CENTERS = np.asarray([(0.0, 0.0, 0.0), (6.0, 6.0, 6.0), (0.0, 8.0, 0.0)])
NOVEL = np.asarray((10.0, -6.0, 5.0))
SPREAD = 0.25


def _blobs(rng, n, centers):
    """n points spread over ``centers`` round-robin; returns (pts, truth)."""
    centers = np.atleast_2d(np.asarray(centers, float))
    truth = np.arange(n) % len(centers)
    return centers[truth] + rng.normal(0, SPREAD, (n, 3)), truth


def _post(base, path, obj):
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_stream_drift_refit_hot_swap(tmp_path):
    rng = np.random.default_rng(42)
    params = HDBSCANParams(
        min_points=8,
        min_cluster_size=100,
        processing_units=2048,
        stream_refit_budget=4000,  # high: drift, not budget, should trigger
    )
    train, _ = _blobs(rng, 5000, CENTERS)
    model = hdbscan.fit(train, params).to_cluster_model(train, params)

    trace = str(tmp_path / "trace.jsonl")
    tracer = Tracer(sinks=[JsonlSink(trace)])
    srv = ClusterServer(
        model, max_batch=64, port=0, tracer=tracer,
        ingest=True, params=params, model_dir=str(tmp_path / "models"),
    ).start()
    base = f"http://{srv.host}:{srv.port}"

    # concurrent /predict load for the whole stream + swap window ---------
    errors, gens = [], []
    stop = threading.Event()

    def hammer():
        lrng = np.random.default_rng(threading.get_ident() % 2**32)
        while not stop.is_set():
            pts, _ = _blobs(lrng, 8, CENTERS)
            try:
                out = _post(base, "/predict", {"points": pts.tolist()})
                gens.append(out["generation"])  # one generation per response
            except Exception as e:  # noqa: BLE001 - the assertion is ==[]
                errors.append(repr(e))
            time.sleep(0.05)

    load = [threading.Thread(target=hammer, daemon=True) for _ in range(2)]
    for t in load:
        t.start()

    try:
        # the 10k stream: 4000 in-distribution, then 6000 shifted ---------
        streamed_shifted = []
        rows = absorbed = 0
        for chunk in range(20):
            if chunk < 8:
                pts, _ = _blobs(rng, 500, CENTERS)
            else:
                pts, _ = _blobs(rng, 500, NOVEL)
                streamed_shifted.append(pts)
            out = _post(base, "/ingest", {"points": pts.tolist()})
            assert out["rows"] == 500
            assert out["absorbed"] + out["buffered"] == out["rows"]
            rows += out["rows"]
            absorbed += out["absorbed"]
        assert rows == 10_000
        shifted = np.concatenate(streamed_shifted)

        # drift must flag and the swap must land --------------------------
        deadline = time.monotonic() + 300
        while srv.generation < 2 and time.monotonic() < deadline:
            time.sleep(0.5)
        assert srv.generation == 2, (
            f"no hot-swap within budget: health={srv.health()}"
        )
        for _ in range(3):  # post-swap traffic definitely sees generation 2
            pts, _ = _blobs(rng, 8, NOVEL)
            assert _post(base, "/predict", {"points": pts.tolist()})[
                "generation"
            ] == 2
    finally:
        stop.set()
        for t in load:
            t.join(timeout=30)
        srv.close()
        tracer.close()

    # zero failed, zero mixed-model requests ------------------------------
    assert errors == []
    assert set(gens) == {1, 2}  # traffic observed on both sides of the swap
    first2 = gens.index(2)
    assert all(g == 2 for g in gens[first2:])  # generations never regress

    # post-swap quality: ARI on shifted data vs a from-scratch fit --------
    eval_pts, truth = _blobs(
        np.random.default_rng(7), 1200, np.vstack([CENTERS, NOVEL[None]])
    )
    swapped = srv.model  # generation-2 artifact now being served
    swap_labels, _ = approximate_predict(swapped, eval_pts)
    scratch_pool = np.concatenate([train, shifted])
    scratch = mr_hdbscan.fit(scratch_pool, params)
    scratch_model = ClusterModel.from_fit_result(scratch, scratch_pool, params)
    scratch_labels, _ = approximate_predict(scratch_model, eval_pts)
    ari_swap = adjusted_rand_index(swap_labels, truth, noise_as_singletons=True)
    ari_scratch = adjusted_rand_index(
        scratch_labels, truth, noise_as_singletons=True
    )
    assert ari_swap >= 0.95 * ari_scratch, (ari_swap, ari_scratch)
    assert ari_swap > 0.5

    # the trace tells the same story and passes the validator -------------
    events, trace_errors = check_trace.validate_trace(trace)
    assert not trace_errors, trace_errors
    stages = {e["stage"] for e in events}
    assert {"stream_ingest", "drift_check", "model_refit",
            "model_swap", "predict_batch"} <= stages
    ingests = [e for e in events if e["stage"] == "stream_ingest"]
    assert sum(e["rows"] for e in ingests) == 10_000
    assert any(e["drifted"] for e in events if e["stage"] == "drift_check")
    refits = [e for e in events if e["stage"] == "model_refit"]
    assert refits and all(e["ok"] for e in refits)
    swaps = [e for e in events if e["stage"] == "model_swap"]
    assert [e["generation"] for e in swaps] == [2]
    assert swaps[0]["reason"] in ("drift", "budget")
    assert swaps[0]["pause_s"] < 0.1  # the swap is a pointer assignment
