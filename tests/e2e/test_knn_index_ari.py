"""e2e ARI acceptance gate for the approximate-neighbor tier (ISSUE 6):
an rpforest fit on the 5k synthetic acceptance dataset must score at least
0.99x the exact-path ARI against ground truth, for both fit families.
"""

import numpy as np

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import exact, mr_hdbscan
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from tests.conftest import make_blobs


def _dataset():
    rng = np.random.default_rng(13)
    return make_blobs(rng, n=5000, d=3, centers=5, spread=0.25)


def _params(**overrides):
    base = dict(min_points=8, min_cluster_size=100, processing_units=2048)
    base.update(overrides)
    return HDBSCANParams(**base)


def test_rpforest_ari_gate_exact_family():
    data, truth = _dataset()
    ari_exact = adjusted_rand_index(
        exact.fit(data, _params()).labels, truth
    )
    ari_rpf = adjusted_rand_index(
        exact.fit(
            data,
            _params(
                knn_index="rpforest", rpf_trees=4, rpf_leaf_size=512,
                rpf_rescan_rounds=1,
            ),
        ).labels,
        truth,
    )
    assert ari_rpf >= 0.99 * ari_exact, (
        f"rpforest ARI {ari_rpf:.4f} < 0.99 x exact ARI {ari_exact:.4f}"
    )


def test_rpforest_ari_gate_mr_family():
    data, truth = _dataset()
    ari_exact = adjusted_rand_index(
        mr_hdbscan.fit(data, _params()).labels, truth
    )
    ari_rpf = adjusted_rand_index(
        mr_hdbscan.fit(
            data,
            _params(
                knn_index="rpforest", rpf_trees=4, rpf_leaf_size=512,
                rpf_rescan_rounds=1,
            ),
        ).labels,
        truth,
    )
    assert ari_rpf >= 0.99 * ari_exact, (
        f"rpforest ARI {ari_rpf:.4f} < 0.99 x mr exact ARI {ari_exact:.4f}"
    )


def test_auto_flips_to_rpforest_and_matches():
    """``knn_index=auto`` above the threshold runs the same deterministic
    forest as an explicit ``rpforest`` fit — identical labels; below the
    threshold it stays bitwise with the exact tier."""
    data, truth = _dataset()
    knobs = dict(rpf_trees=4, rpf_leaf_size=512, rpf_rescan_rounds=1)
    auto = exact.fit(
        data, _params(knn_index="auto", knn_index_threshold=1000, **knobs)
    )
    explicit = exact.fit(data, _params(knn_index="rpforest", **knobs))
    np.testing.assert_array_equal(auto.labels, explicit.labels)

    below = exact.fit(
        data,
        _params(knn_index="auto", knn_index_threshold=10**9, **knobs),
    )
    exact_fit = exact.fit(data, _params())
    np.testing.assert_array_equal(below.labels, exact_fit.labels)
