"""Telemetry artifacts end-to-end: ``--trace-out``/``--report`` round-trip
through the CLI on a tiny synthetic dataset (tier-1), the zero-file-I/O
guarantee with both flags absent, and the multi-process merged report
(skipped where the multi-controller collectives backend is unavailable)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from hdbscan_tpu.cli import main
from scripts import check_trace

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _write_blobs(tmp_path, n_per=60, centers=((0, 0, 0), (6, 6, 6))):
    rng = np.random.default_rng(5)
    pts = np.concatenate([rng.normal(c, 0.3, size=(n_per, 3)) for c in centers])
    path = str(tmp_path / "blobs.txt")
    np.savetxt(path, pts, fmt="%.6f")
    return path, len(pts)


class TestTelemetryCLI:
    def test_exact_path_roundtrip(self, tmp_path):
        """Every JSONL line parses with {schema, stage, wall_s}; the report's
        per-phase walls equal the trace sums within 1e-6 (the validator's
        cross-check); manifest/memory/compile figures are present."""
        dataset, n = _write_blobs(tmp_path)
        trace = str(tmp_path / "trace.jsonl")
        report = str(tmp_path / "report.json")
        rc = main(
            [
                f"file={dataset}",
                "minPts=4",
                "minClSize=10",
                "processing_units=200",
                f"out_dir={tmp_path / 'out'}",
                "--trace-out",
                trace,
                f"--report={report}",
            ]
        )
        assert rc == 0
        events, errors = check_trace.validate_trace(trace)
        assert errors == [], errors
        assert events, "trace must carry events"
        stages = {e["stage"] for e in events}
        assert {"load_points", "block_edges", "fit", "write_outputs"} <= stages
        rep, errors = check_trace.validate_report(report, trace_events=events)
        assert errors == [], errors

        man = rep["manifest"]
        assert man["params"]["min_points"] == 4
        assert man["argv"][0] == f"file={dataset}"
        assert "--trace-out" in man["argv"]  # argv recorded pre-pop
        assert man["backends"]["default_backend"] == "cpu"
        assert man["topology"]["device_count"] >= 1
        assert rep["phases"]["fit"]["wall_s"] > 0
        assert rep["memory"]["start"]["source"] in ("memory_stats", "live_arrays")
        # Compile tracking: the run jits fresh shapes, so at least one phase
        # must carry a jit_compiles attribution.
        assert any("jit_compiles" in row for row in rep["phases"].values())
        # load_points reports the dataset shape it actually read.
        load = next(e for e in events if e["stage"] == "load_points")
        assert load["rows"] == n and load["dims"] == 3

    def test_mr_path_roundtrip(self, tmp_path):
        """The recursive-sampling path traces its level/boundary stages into
        the same artifact pair."""
        dataset, n = _write_blobs(tmp_path, n_per=80)
        trace = str(tmp_path / "trace.jsonl")
        report = str(tmp_path / "report.json")
        rc = main(
            [
                f"file={dataset}",
                "minPts=4",
                "minClSize=20",
                "processing_units=60",
                "k=0.3",
                "seed=1",
                f"out_dir={tmp_path / 'out'}",
                "--trace-out",
                trace,
                "--report",
                report,
            ]
        )
        assert rc == 0
        events, errors = check_trace.validate_trace(trace)
        assert errors == [], errors
        stages = {e["stage"] for e in events}
        assert "level" in stages and "fit" in stages
        _, errors = check_trace.validate_report(report, trace_events=events)
        assert errors == [], errors

    def test_ring_scan_roundtrip(self, tmp_path):
        """Forced-8-device CPU ring run (``scan_backend=ring``): ring scan
        events land in the trace, satisfy the validator's ring invariants
        (``ppermute_steps == devices - 1`` per round, per-device ``seq``
        monotonic), and the report round-trips with the backend recorded."""
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("ring scan needs a multi-device mesh")
        dataset, n = _write_blobs(tmp_path, n_per=80)
        trace = str(tmp_path / "trace.jsonl")
        report = str(tmp_path / "report.json")
        rc = main(
            [
                f"file={dataset}",
                "minPts=4",
                "minClSize=20",
                "processing_units=60",
                "k=0.3",
                "seed=1",
                "scan_backend=ring",
                f"out_dir={tmp_path / 'out'}",
                "--trace-out",
                trace,
                "--report",
                report,
            ]
        )
        assert rc == 0
        events, errors = check_trace.validate_trace(trace)
        assert errors == [], errors
        stages = {e["stage"] for e in events}
        assert "ring_device_wall" in stages
        summaries = [e for e in events if "ppermute_steps" in e]
        assert summaries, "ring run must emit ring summary events"
        n_dev = len(jax.devices())
        for ev in summaries:
            assert ev["devices"] == n_dev
            assert ev["ppermute_steps"] == n_dev - 1
        # Every summary is mirrored by one wall event per device.
        walls = [e for e in events if e["stage"] == "ring_device_wall"]
        assert len(walls) == len(summaries) * n_dev
        rep, errors = check_trace.validate_report(report, trace_events=events)
        assert errors == [], errors
        assert rep["manifest"]["backends"]["scan_backend"] == "ring"

    def test_no_flags_no_artifacts(self, tmp_path):
        """Both flags absent: the run creates the five canonical outputs and
        NOTHING else — no trace, no report, no stray telemetry files."""
        dataset, _ = _write_blobs(tmp_path)
        out = tmp_path / "out"
        before = set(os.listdir(tmp_path))
        rc = main(
            [
                f"file={dataset}",
                "minPts=4",
                "minClSize=10",
                "processing_units=200",
                f"out_dir={out}",
            ]
        )
        assert rc == 0
        assert set(os.listdir(tmp_path)) - before == {"out"}
        assert not [f for f in os.listdir(out) if f.endswith((".jsonl", ".json"))]

    def test_summary_prints_all_stages(self, tmp_path, capsys):
        """The end-of-run phase summary lists every traced stage (no
        allowlist), expensive first."""
        dataset, _ = _write_blobs(tmp_path)
        rc = main(
            [
                f"file={dataset}",
                "minPts=4",
                "minClSize=10",
                "processing_units=200",
                f"out_dir={tmp_path / 'out'}",
            ]
        )
        assert rc == 0
        err = capsys.readouterr().err
        assert "phases:" in err
        for stage in ("load_points", "block_edges", "fit", "write_outputs"):
            assert f"{stage}: n=" in err
        # Sorted by summed wall descending.
        walls = [
            float(line.split("wall_s=")[1])
            for line in err.split("phases:")[1].splitlines()
            if "wall_s=" in line
        ]
        assert walls == sorted(walls, reverse=True)


class TestMultiProcessMergedReport:
    def test_two_process_merged_report(self, tmp_path):
        """2 controllers x 2 virtual devices: each rank writes
        ``trace.<rank>.jsonl``; the coordinator's report carries per-host
        phase walls for both ranks."""
        from hdbscan_tpu.parallel.distributed import (
            communicate_all,
            free_local_port,
            hermetic_child_env,
        )

        rng = np.random.default_rng(7)
        pts = np.concatenate(
            [rng.normal(c, 0.4, size=(400, 3)) for c in ((0, 0, 0), (8, 0, 0), (0, 8, 8))]
        )
        dataset = str(tmp_path / "blobs.txt")
        np.savetxt(dataset, pts, fmt="%.6f")
        trace = str(tmp_path / "trace.jsonl")
        report = str(tmp_path / "report.json")
        port = free_local_port()
        args = lambda pid: [  # noqa: E731
            f"file={dataset}",
            "minPts=4",
            "minClSize=50",
            "processing_units=256",
            "k=0.1",
            "seed=3",
            f"out_dir={tmp_path / 'out'}",
            f"clusterName=127.0.0.1:{port},{pid},2",
            "--trace-out",
            trace,
            "--report",
            report,
        ]
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "hdbscan_tpu", *args(pid)],
                env=hermetic_child_env(2, repo_root=REPO),
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for pid in (0, 1)
        ]
        outs = communicate_all(procs)
        if any(p.returncode != 0 for p in procs):
            # The multi-controller collectives backend does not come up in
            # every container (pre-existing, tests/e2e/test_multiprocess.py
            # fails the same way); the merge logic itself is covered by
            # tests/unit/test_telemetry.py::TestHostMerge.
            pytest.skip(
                "multi-controller run unavailable here: "
                + outs[0][1][-400:].replace("\n", " | ")
            )

        # One trace file per rank, every line valid.
        per_rank_events = {}
        for pid in (0, 1):
            rank_path = str(tmp_path / f"trace.{pid}.jsonl")
            assert os.path.exists(rank_path), f"rank {pid} trace missing"
            events, errors = check_trace.validate_trace(rank_path)
            assert errors == [], errors
            assert all(e["process"] == pid for e in events)
            per_rank_events[pid] = events

        rep = json.load(open(report))
        assert rep["schema"].startswith("hdbscan-tpu-report/")
        assert rep["manifest"]["topology"]["process_count"] == 2
        hosts = rep["per_host"]
        assert set(hosts) == {"0", "1"}
        for pid, events in per_rank_events.items():
            table = hosts[str(pid)]
            fit_sum = sum(
                e["wall_s"] for e in events if e["stage"] == "fit"
            )
            assert abs(table["fit"]["wall_s"] - fit_sum) < 1e-6
            assert table["fit"]["count"] >= 1
