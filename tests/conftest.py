"""Test env: 8 virtual CPU devices (the tpu-native analog of Spark local[*],
SURVEY.md §4) and float64 for parity with the reference's Java doubles."""

import os

# Force CPU even when the session environment pins JAX_PLATFORMS to a TPU
# plugin: tests validate semantics on an 8-device virtual mesh in float64.
# The environment's sitecustomize imports jax at interpreter start, so env
# vars alone are too late — use jax.config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


def pytest_configure(config):
    # Tier-1 (ROADMAP) runs `-m 'not slow'`; the slow lane holds the
    # long e2e legs (multi-second sustained-load / full-stream runs).
    # Opt in with `-m slow` or by dropping the filter.
    config.addinivalue_line(
        "markers",
        "slow: long e2e leg, excluded from tier-1 (`-m 'not slow'`); "
        "run with `pytest -m slow` or no marker filter",
    )


#: The reference checkout's bundled 149x4 dataset. Optional at test time:
#: containers without the checkout SKIP the golden/oracle tests that need it
#: instead of erroring (tests that hard-code the path carry their own
#: ``skipif``, e.g. tests/e2e/test_cli.py).
REFERENCE_DATASET = "/root/reference/数据集/dataset.txt"


@pytest.fixture(scope="session")
def iris():
    """The bundled 149x4 dataset (reference 数据集/dataset.txt)."""
    if not os.path.exists(REFERENCE_DATASET):
        pytest.skip(f"reference dataset not available ({REFERENCE_DATASET})")
    return np.loadtxt(REFERENCE_DATASET)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def make_blobs(rng, n=120, d=3, centers=3, spread=0.15):
    """Tiny gaussian blobs helper shared by unit tests."""
    centers_xy = rng.uniform(-3, 3, size=(centers, d))
    assign = rng.integers(0, centers, size=n)
    return centers_xy[assign] + rng.normal(0, spread, size=(n, d)), assign
