"""Golden tests: exact single-block path on the bundled 149x4 Iris file,
validated against the NumPy oracle (reference params minPts=4, minClSize=4 —
the hard-coded demo configuration, main/Main.java:71)."""

import numpy as np
import pytest

from hdbscan_tpu.config import HDBSCANParams
from hdbscan_tpu.models import hdbscan as model
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from tests.oracle import oracle_hdbscan as O


@pytest.fixture(scope="module")
def iris_result(iris):
    params = HDBSCANParams(min_points=4, min_cluster_size=4)
    return model.fit(iris, params)


@pytest.fixture(scope="module")
def iris_oracle(iris):
    return O.hdbscan_oracle(iris, 4, 4)


def test_core_distances(iris_result, iris_oracle):
    np.testing.assert_allclose(
        iris_result.core_distances, iris_oracle["core"], rtol=1e-9
    )


def test_mst_total_weight(iris_result, iris_oracle):
    got = iris_result.mst[2].sum()
    u, v, w = iris_oracle["mst"]
    want = w[: len(iris_result.mst[2])].sum()  # oracle includes self edges at tail
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_flat_partition_matches_oracle(iris_result, iris_oracle):
    assert adjusted_rand_index(iris_result.labels, iris_oracle["labels"]) == 1.0
    # Iris has 2 natural density clusters at minPts=4 (setosa vs rest)
    n_clusters = len(set(iris_result.labels) - {0})
    assert n_clusters >= 2


def test_glosh_matches_oracle(iris_result, iris_oracle):
    # Device (dot-trick) and oracle (diff-based) euclidean distances agree to
    # ~1e-11 relative; tie grouping makes the tree structure identical, the
    # level values keep that float noise.
    np.testing.assert_allclose(
        iris_result.outlier_scores, iris_oracle["glosh"], rtol=1e-6, atol=1e-8
    )


def test_exit_levels_match_oracle(iris_result, iris_oracle):
    np.testing.assert_allclose(
        iris_result.tree.point_exit_level, iris_oracle["exit_level"], rtol=1e-6
    )


def test_output_files(tmp_path, iris, iris_result):
    params = HDBSCANParams(
        input_file="/root/reference/数据集/dataset.txt",
        min_points=4,
        min_cluster_size=4,
        out_dir=str(tmp_path),
    )
    paths = model.write_outputs(iris_result, params)
    assert set(paths) == {"hierarchy", "tree", "partition", "outlier_scores", "visualization"}
    # partition file round-trips
    flat = np.loadtxt(paths["partition"], delimiter=",")
    np.testing.assert_array_equal(flat, iris_result.labels)
    # hierarchy: first column descending epsilon, labels in range
    rows = [l.split(",") for l in open(paths["hierarchy"]).read().splitlines()]
    eps = [float(r[0]) for r in rows]
    assert eps == sorted(eps, reverse=True)
    assert all(len(r) == 1 + len(iris) for r in rows)
    # tree file parses, has root with parent 0
    tree_rows = [l.split(",") for l in open(paths["tree"]).read().splitlines()]
    assert tree_rows[0][0] == "1" and tree_rows[0][-1] == "0"
    # outlier scores sorted ascending
    scores = [float(l.split(",")[0]) for l in open(paths["outlier_scores"])]
    assert scores == sorted(scores)


def test_alternate_metrics_run(iris):
    """Distance plug-in configs (BASELINE.json config 4)."""
    for metric in ("manhattan", "cosine"):
        params = HDBSCANParams(min_points=4, min_cluster_size=4, dist_function=metric)
        res = model.fit(iris, params)
        oracle = O.hdbscan_oracle(iris, 4, 4, metric=metric)
        assert adjusted_rand_index(res.labels, oracle["labels"]) == 1.0


class TestOutputFileContracts:
    def test_tree_file_offsets_point_at_first_hierarchy_row(self, iris, tmp_path):
        """The tree file's character offsets must land on the first hierarchy
        row where each cluster's label appears (Cluster.java:165 contract)."""
        from hdbscan_tpu.config import HDBSCANParams
        from hdbscan_tpu.models import hdbscan
        from hdbscan_tpu.utils import io as io_mod

        params = HDBSCANParams(min_points=4, min_cluster_size=4)
        res = hdbscan.fit(iris, params)
        hpath = str(tmp_path / "h.csv")
        offsets = io_mod.write_hierarchy_file(hpath, res.tree, compact=False)
        blob = open(hpath).read()
        for label, off in offsets.items():
            line = blob[off:].split("\n", 1)[0]
            labels_at_line = line.split(",")[1:]
            assert str(label) in labels_at_line, (label, off)
            # FIRST appearance: no earlier row may contain the label.
            for prior in blob[:off].splitlines():
                assert str(label) not in prior.split(",")[1:], (label, off)
