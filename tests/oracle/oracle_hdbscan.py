"""Independent NumPy oracle for HDBSCAN* semantics.

A deliberately literal, slow re-implementation of the reference algorithms,
following the Java control flow (Prim MST, top-down tie-grouped edge removal
with BFS component discovery) rather than the TPU-friendly design of
``hdbscan_tpu`` (Borůvka, bottom-up union-find + contraction). The two paths
share no code, so agreement is a real check.

Mirrors:
- ``HDBSCANStar.calculateCoreDistances`` (HDBSCANStar.java:71-106), with the
  per-point kNN buffer reset the reference accidentally hoisted.
- ``HDBSCANStar.constructMST`` (HDBSCANStar.java:124-205) incl. self edges.
- ``HdbscanDataBubbles.constructClusterTree`` (HdbscanDataBubbles.java:256-374)
  with each post-removal component processed once.
- ``Cluster.detachPoints`` / ``Cluster.propagate`` (Cluster.java:80-142).
- ``HDBSCANStar.calculateOutlierScores`` (HDBSCANStar.java:653-686).

Root birth level is +inf (the correct-math default documented in
hdbscan_tpu/core/tree.py).
"""

from __future__ import annotations

import numpy as np

INF = np.inf


def pairwise(x, y, metric="euclidean"):
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    diff = x[:, None, :] - y[None, :, :]
    if metric == "euclidean":
        return np.sqrt((diff**2).sum(-1))
    if metric == "manhattan":
        return np.abs(diff).sum(-1)
    if metric == "supremum":
        return np.abs(diff).max(-1)
    if metric == "cosine":
        num = x @ y.T
        den = np.linalg.norm(x, axis=1)[:, None] * np.linalg.norm(y, axis=1)[None, :]
        return 1.0 - num / den
    if metric == "pearson":
        xc = x - x.mean(1, keepdims=True)
        yc = y - y.mean(1, keepdims=True)
        return pairwise(xc, yc, "cosine")
    raise ValueError(metric)


def core_distances(data, k, metric="euclidean"):
    """Largest of the k-1 smallest distances including self-distance 0."""
    n = len(data)
    if k <= 1:
        return np.zeros(n)
    d = pairwise(data, data, metric)
    srt = np.sort(d, axis=1)
    kk = min(k - 1, n)
    return srt[:, kk - 1]


def prim_mst(data, core, self_edges=True, metric="euclidean"):
    """Literal translation of HDBSCANStar.constructMST. Returns (u, v, w)."""
    n = len(data)
    d = pairwise(data, data, metric)
    mrd = np.maximum(d, np.maximum(core[:, None], core[None, :]))
    attached = np.zeros(n, bool)
    nearest_nb = np.zeros(n, np.int64)
    nearest_d = np.full(n, INF)
    current = n - 1
    attached[current] = True
    us, vs, ws = [], [], []
    for _ in range(n - 1):
        unatt = ~attached
        cand = np.where(unatt)[0]
        upd = mrd[current, cand] < nearest_d[cand]
        nearest_d[cand[upd]] = mrd[current, cand[upd]]
        nearest_nb[cand[upd]] = current
        # reference takes <=, scanning in index order -> last min wins
        bi = cand[0]
        bd = nearest_d[bi]
        for j in cand[1:]:
            if nearest_d[j] <= bd:
                bd = nearest_d[j]
                bi = j
        attached[bi] = True
        us.append(bi)
        vs.append(nearest_nb[bi])
        ws.append(nearest_d[bi])
        current = bi
    if self_edges:
        for i in range(n):
            us.append(i)
            vs.append(i)
            ws.append(core[i])
    return np.array(us), np.array(vs), np.array(ws, np.float64)


class OracleCluster:
    def __init__(self, label, parent, birth, num_points):
        self.label = label
        self.parent = parent
        self.birth = birth
        self.death = 0.0
        self.num_points = num_points
        self.alive = num_points
        self.stability = 0.0
        self.members_at_birth = None  # snapshot
        self.has_children = False
        self.prop_stability = 0.0
        self.prop_cons = 0
        self.cons = 0
        self.lowest_child_death = INF
        self.prop_descendants = []

    def detach(self, count, level):
        inv_birth = 0.0 if np.isinf(self.birth) else 1.0 / self.birth
        inv_level = INF if level == 0 else 1.0 / level
        self.stability += count * (inv_level - inv_birth)
        self.alive -= count
        if self.alive <= 0:
            self.death = level


TIE_RTOL = 1e-9  # same tie-grouping contract as hdbscan_tpu.core.tree


def condensed_tree(n, u, v, w, mcs, point_weights=None, tie_rtol=TIE_RTOL):
    """Top-down tie-grouped edge removal; returns (clusters dict label->OracleCluster,
    point_exit_level, point_last_cluster)."""
    if point_weights is None:
        point_weights = np.ones(n)
    adj = [set() for _ in range(n)]
    real = [(float(w[i]), int(u[i]), int(v[i])) for i in range(len(w))]
    self_alive = np.zeros(n, bool)  # un-removed self edges (HDBSCANStar.java:196-203)
    for wt, a, b in real:
        if a != b:
            adj[a].add(b)
            adj[b].add(a)
        else:
            self_alive[a] = True
    has_self_edges = bool(self_alive.any())
    # sort descending by weight
    order = sorted(range(len(real)), key=lambda i: (-real[i][0], real[i][1], real[i][2]))
    labels = np.ones(n, np.int64)
    clusters = {1: OracleCluster(1, -1, INF, float(point_weights.sum()))}
    clusters[1].members_at_birth = set(range(n))
    next_label = 2
    exit_level = np.zeros(n)
    last_cluster = np.ones(n, np.int64)
    i = 0
    while i < len(order):
        wt = real[order[i]][0]
        affected = {}
        while i < len(order) and abs(real[order[i]][0] - wt) <= tie_rtol * max(abs(wt), 1e-300):
            _, a, b = real[order[i]]
            if a == b:
                self_alive[a] = False
            else:
                adj[a].discard(b)
                adj[b].discard(a)
            i += 1
            if labels[a] == 0:
                continue
            affected.setdefault(labels[a], set()).update((a, b))
        for plabel, verts in affected.items():
            seen = set()
            comps = []
            for r in sorted(verts):
                if r in seen:
                    continue
                comp = {r}
                queue = [r]
                while queue:
                    x = queue.pop()
                    for y in adj[x]:
                        if y not in comp:
                            comp.add(y)
                            queue.append(y)
                seen |= comp
                comps.append(comp)
            parent = clusters[plabel]

            def is_big(c):
                # Noise when size < minClusterSize OR no edges remain
                # (a lone vertex whose self edge is gone): the reference's
                # "!anyEdges" rule, HDBSCANStar.java:361. Connected multi-
                # vertex components always have edges; the rule only exists
                # in the self-edge (points) tree — the live bubble tree has
                # no self edges and lets a heavy singleton live on
                # (HdbscanDataBubbles.java:330-352).
                if point_weights[list(c)].sum() < mcs:
                    return False
                if len(c) == 1 and has_self_edges:
                    (p,) = c
                    return bool(adj[p]) or bool(self_alive[p])
                return True

            big = [c for c in comps if is_big(c)]
            small = [c for c in comps if not is_big(c)]
            if len(big) >= 2:
                parent.has_children = True
                for c in big:
                    cl = OracleCluster(next_label, plabel, wt, point_weights[list(c)].sum())
                    cl.members_at_birth = set(c)
                    clusters[next_label] = cl
                    for p in c:
                        labels[p] = next_label
                    parent.detach(point_weights[list(c)].sum(), wt)
                    next_label += 1
                for c in small:
                    for p in c:
                        labels[p] = 0
                        exit_level[p] = wt
                        last_cluster[p] = plabel
                    parent.detach(point_weights[list(c)].sum(), wt)
            else:
                for c in small:
                    for p in c:
                        labels[p] = 0
                        exit_level[p] = wt
                        last_cluster[p] = plabel
                    parent.detach(point_weights[list(c)].sum(), wt)
    # points never detached keep exit 0, last = current label
    for p in range(n):
        if labels[p] != 0:
            last_cluster[p] = labels[p]
    return clusters, exit_level, last_cluster


def propagate(clusters):
    for label in sorted(clusters, reverse=True):
        cl = clusters[label]
        if cl.lowest_child_death == INF:
            cl.lowest_child_death = cl.death
        if cl.parent == -1 or cl.parent not in clusters:
            continue
        par = clusters[cl.parent]
        par.lowest_child_death = min(par.lowest_child_death, cl.lowest_child_death)
        if (not cl.has_children) or cl.cons > cl.prop_cons or (
            cl.cons == cl.prop_cons and cl.stability >= cl.prop_stability
        ):
            par.prop_cons += cl.cons
            par.prop_stability += cl.stability
            par.prop_descendants.append(label)
        else:
            par.prop_cons += cl.prop_cons
            par.prop_stability += cl.prop_stability
            par.prop_descendants.extend(cl.prop_descendants)
    return clusters[1].prop_descendants if 1 in clusters else []


def flat_from_solution(n, clusters, solution):
    out = np.zeros(n, np.int64)
    for label in solution:
        for p in clusters[label].members_at_birth:
            out[p] = label
    return out


def glosh(clusters, exit_level, last_cluster):
    n = len(exit_level)
    scores = np.zeros(n)
    for p in range(n):
        cl = clusters[last_cluster[p]]
        eps_max = cl.lowest_child_death
        eps = exit_level[p]
        scores[p] = 0.0 if eps == 0 else 1.0 - eps_max / eps
    return scores


def hdbscan_oracle(data, min_pts, min_cluster_size, metric="euclidean"):
    """Full single-block pipeline: returns dict of everything."""
    core = core_distances(data, min_pts, metric)
    u, v, w = prim_mst(data, core, self_edges=True, metric=metric)
    clusters, exit_level, last_cluster = condensed_tree(
        len(data), u, v, w, min_cluster_size
    )
    solution = propagate(clusters)
    labels = flat_from_solution(len(data), clusters, solution)
    scores = glosh(clusters, exit_level, last_cluster)
    return dict(
        core=core,
        mst=(u, v, w),
        clusters=clusters,
        solution=solution,
        labels=labels,
        exit_level=exit_level,
        last_cluster=last_cluster,
        glosh=scores,
    )
